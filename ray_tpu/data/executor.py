"""Streaming executor: turns a logical plan into pipelined task waves.

Mirrors the reference's streaming execution model (reference:
python/ray/data/_internal/execution/streaming_executor.py:72 — operators
pull block refs from upstream, launch bounded numbers of remote tasks,
and hand refs downstream before the whole input is materialized), with
the reference's map-fusion optimization (logical/rules/operator_fusion):
consecutive row/batch maps run as one task per block.

Blocks never pass through the driver on the hot path — stages exchange
ObjectRefs; values stay in worker memory / the shared-memory store.
"""

from __future__ import annotations

import collections
import logging
from typing import Iterator

import numpy as np

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data import plan as P

logger = logging.getLogger("ray_tpu.data")

_FUSABLE = {"map_batches", "map", "filter", "flat_map", "add_column",
            "drop_columns", "select_columns"}


class DataContext:
    """Execution knobs (reference: python/ray/data/context.py DataContext)."""

    _instance = None

    def __init__(self):
        self.prefetch_blocks = 4          # per-stage in-flight task window
        self.default_parallelism = None   # None → from cluster CPUs
        self.shuffle_partitions = None    # None → keep input partition count
        self.min_parallelism = 2

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = DataContext()
        return cls._instance

    def parallelism(self) -> int:
        if self.default_parallelism:
            return self.default_parallelism
        try:
            cpus = int(ray_tpu.cluster_resources().get("CPU", 0))
        except Exception:  # noqa: BLE001
            logger.debug(
                "cluster resource probe failed; using default parallelism"
            )
            cpus = 0
        return max(self.min_parallelism, cpus or 4)


# --------------------------------------------------------------------------
# Remote kernels (run in worker processes).
# --------------------------------------------------------------------------

def _apply_chain(chain: list, blk: B.Block) -> B.Block:
    for op in chain:
        kind = op[0]
        if kind == "map_batches":
            _, fn, batch_size, batch_format, fn_args, fn_kwargs = op
            if batch_size is None or B.num_rows(blk) <= batch_size:
                blk = B.from_batch(fn(B.to_batch(blk, batch_format), *fn_args, **fn_kwargs))
            else:
                outs = []
                for s in range(0, B.num_rows(blk), batch_size):
                    piece = B.slice_block(blk, s, s + batch_size)
                    outs.append(B.from_batch(fn(B.to_batch(piece, batch_format), *fn_args, **fn_kwargs)))
                blk = B.concat(outs)
        elif kind == "map":
            blk = B.from_rows(op[1](r) for r in B.to_rows(blk))
        elif kind == "filter":
            fn = op[1]
            keep = np.fromiter((bool(fn(r)) for r in B.to_rows(blk)), dtype=bool,
                               count=B.num_rows(blk))
            blk = B.take_idx(blk, np.nonzero(keep)[0])
        elif kind == "flat_map":
            fn = op[1]
            rows = []
            for r in B.to_rows(blk):
                rows.extend(fn(r))
            blk = B.from_rows(rows)
        elif kind == "add_column":
            _, name, fn = op
            blk = dict(B.ensure_numpy(blk))
            blk[name] = B._as_array(fn(dict(blk)))
        elif kind == "drop_columns":
            if B._is_table(blk):
                blk = blk.drop_columns(
                    [c for c in op[1] if c in blk.column_names]
                )
            else:
                blk = {k: v for k, v in blk.items() if k not in op[1]}
        elif kind == "select_columns":
            if B._is_table(blk):
                blk = blk.select(op[1])
            else:
                blk = {k: blk[k] for k in op[1]}
        else:
            raise AssertionError(kind)
    return blk


@ray_tpu.remote
def _exec_read(task, chain):
    return _apply_chain(chain, task())


@ray_tpu.remote
def _exec_chain(chain, blk):
    return _apply_chain(chain, blk)


@ray_tpu.remote
class _MapActor:
    """Actor-compute map worker (reference: actor_pool_map_operator.py)."""

    def __init__(self, fn_cls, ctor_args, chain_rest):
        self.fn = fn_cls(*ctor_args)
        self.chain_rest = chain_rest

    def apply(self, batch_size, batch_format, fn_args, fn_kwargs, blk):
        first = ("map_batches", self.fn, batch_size, batch_format, fn_args, fn_kwargs)
        return _apply_chain([first] + list(self.chain_rest), blk)


@ray_tpu.remote
def _count_rows(blk):
    return B.num_rows(blk)


@ray_tpu.remote
def _head(blk, n):
    return B.slice_block(blk, 0, n)


@ray_tpu.remote
def _slice_concat(meta, *blks):
    # meta: list of (input_index, start, end) making up this output partition
    return B.concat([B.slice_block(blks[i], s, e) for i, s, e in meta])


@ray_tpu.remote
def _shuffle_map(n, seed, blk):
    rng = np.random.default_rng(seed)
    nr = B.num_rows(blk)
    assign = rng.integers(0, n, size=nr)
    parts = tuple(B.take_idx(blk, np.nonzero(assign == j)[0]) for j in range(n))
    return parts if n > 1 else parts[0]


@ray_tpu.remote
def _shuffle_reduce(seed, *parts):
    blk = B.concat(list(parts))
    rng = np.random.default_rng(seed)
    return B.take_idx(blk, rng.permutation(B.num_rows(blk)))


@ray_tpu.remote
def _sample_keys(key, k, blk):
    blk = B.ensure_numpy(blk)
    nr = B.num_rows(blk)
    if nr == 0:
        return np.array([])
    idx = np.linspace(0, nr - 1, num=min(k, nr)).astype(np.int64)
    return blk[key][idx]


@ray_tpu.remote
def _range_part(key, boundaries, blk):
    blk = B.ensure_numpy(blk)
    n = len(boundaries) + 1
    keys = blk[key]
    assign = np.searchsorted(boundaries, keys, side="right")
    parts = tuple(B.take_idx(blk, np.nonzero(assign == j)[0]) for j in range(n))
    return parts if n > 1 else parts[0]


@ray_tpu.remote
def _merge_sorted(key, descending, *parts):
    blk = B.ensure_numpy(B.concat(list(parts)))
    order = np.argsort(blk[key], kind="stable") if blk else np.array([], dtype=np.int64)
    if descending:
        order = order[::-1]
    return B.take_idx(blk, order)


def _stable_hash(k, n: int) -> int:
    """Deterministic across processes (builtin str hash is per-process
    randomized, which would scatter equal keys across partitions)."""
    if isinstance(k, (int, np.integer)):
        return int(k) % n
    import zlib

    return zlib.crc32(repr(k).encode()) % n


@ray_tpu.remote
def _hash_part(key, n, blk):
    blk = B.ensure_numpy(blk)
    if not blk:
        return tuple({} for _ in range(n)) if n > 1 else {}
    keys = blk[key]
    hashes = np.array([_stable_hash(k, n) for k in keys.tolist()], dtype=np.int64)
    parts = tuple(B.take_idx(blk, np.nonzero(hashes == j)[0]) for j in range(n))
    return parts if n > 1 else parts[0]


def _agg_one(kind, vals):
    if kind == "count":
        return len(vals)
    return getattr(np, kind)(vals) if len(vals) else None


@ray_tpu.remote
def _agg_partition(key, aggs, *parts):
    blk = B.ensure_numpy(B.concat(list(parts)))
    if not blk:
        return {}
    rows = []
    if key is None:
        row = {}
        for kind, col, out in aggs:
            row[out] = _agg_one(kind, blk[col] if col else next(iter(blk.values())))
        rows.append(row)
    else:
        keys = blk[key]
        uniq, inv = np.unique(keys, return_inverse=True)
        for gi, kval in enumerate(uniq):
            idx = np.nonzero(inv == gi)[0]
            row = {key: kval}
            for kind, col, out in aggs:
                row[out] = _agg_one(kind, blk[col][idx] if col else idx)
            rows.append(row)
    return B.from_rows(rows)


@ray_tpu.remote
def _map_groups(key, fn, batch_format, *parts):
    blk = B.ensure_numpy(B.concat(list(parts)))
    if not blk:
        return {}
    keys = blk[key]
    uniq, inv = np.unique(keys, return_inverse=True)
    outs = []
    for gi in range(len(uniq)):
        idx = np.nonzero(inv == gi)[0]
        group = B.take_idx(blk, idx)
        outs.append(B.from_batch(fn(B.to_batch(group, batch_format))))
    return B.concat(outs)


@ray_tpu.remote
def _zip_blocks(meta, left, *rights):
    right = B.ensure_numpy(
        B.concat([B.slice_block(rights[i], s, e) for i, s, e in meta])
    )
    out = dict(B.ensure_numpy(left))
    for k, v in right.items():
        out[k if k not in out else k + "_1"] = v
    return out


# --------------------------------------------------------------------------
# Driver-side stages.
# --------------------------------------------------------------------------

def _windowed(submit, inputs, window: int) -> Iterator:
    """Submit with a bounded in-flight window — the backpressure primitive
    (reference: backpressure_policy/concurrency_cap_backpressure_policy.py)."""
    pending = collections.deque()
    for item in inputs:
        pending.append(submit(item))
        if len(pending) >= window:
            yield pending.popleft()
    while pending:
        yield pending.popleft()


def _chain_spec(ops: list[P.Op]) -> list:
    chain = []
    for op in ops:
        if op.kind == "map_batches":
            chain.append(("map_batches", op.fn, op.batch_size, op.batch_format,
                          op.fn_args, op.fn_kwargs))
        elif op.kind in ("map", "filter", "flat_map"):
            chain.append((op.kind, op.fn))
        elif op.kind == "add_column":
            chain.append(("add_column", op.col_name, op.fn))
        elif op.kind in ("drop_columns", "select_columns"):
            chain.append((op.kind, op.cols))
        else:
            raise AssertionError(op.kind)
    return chain


def _counts(refs: list) -> list[int]:
    return ray_tpu.get([_count_rows.remote(r) for r in refs])


def _slice_plan(counts: list[int], n_out: int) -> list[list[tuple]]:
    """Global row-ranges → n_out balanced output partitions."""
    total = sum(counts)
    starts = [round(j * total / n_out) for j in range(n_out + 1)]
    plans: list[list[tuple]] = [[] for _ in range(n_out)]
    offset = 0
    for i, c in enumerate(counts):
        for j in range(n_out):
            lo, hi = max(starts[j], offset), min(starts[j + 1], offset + c)
            if lo < hi:
                plans[j].append((i, lo - offset, hi - offset))
        offset += c
    return plans


def execute(plan: P.LogicalPlan, ctx: DataContext | None = None) -> Iterator:
    """Yield output block refs for the plan, streaming where possible."""
    ctx = ctx or DataContext.get_current()
    ops = list(plan.ops)
    stream: Iterator = iter(())
    i = 0
    while i < len(ops):
        op = ops[i]
        if op.kind == "ref_source":
            stream = iter(op.refs)
            i += 1
            continue
        # ---- fuse a run of map-like ops into one stage
        is_actor_map = op.kind == "map_batches" and op.compute == "actors"
        if (op.kind in _FUSABLE and not is_actor_map) or op.kind == "read":
            j = i + (1 if op.kind == "read" else 0)
            while j < len(ops) and ops[j].kind in _FUSABLE:
                # actor-compute map_batches breaks fusion at its boundary
                if ops[j].kind == "map_batches" and ops[j].compute == "actors":
                    break
                j += 1
            fused = ops[i:j] if op.kind != "read" else ops[i + 1 : j]
            chain = _chain_spec(fused)
            cap = None  # explicit user concurrency cap survives fusion
            for f in fused:
                if getattr(f, "concurrency", None):
                    cap = f.concurrency if cap is None else min(cap, f.concurrency)
            if op.kind == "read":
                window = cap if cap else max(ctx.prefetch_blocks,
                                             ctx.parallelism())
                stream = _windowed(lambda t, c=chain: _exec_read.remote(t, c),
                                   iter(op.tasks), window)
            else:
                window = cap if cap else ctx.prefetch_blocks
                stream = _windowed(lambda r, c=chain: _exec_chain.remote(c, r),
                                   stream, window)
            i = j
            continue
        if op.kind == "map_batches" and op.compute == "actors":
            # actor pool stage: round-robin blocks over n stateful actors
            j = i + 1
            while j < len(ops) and ops[j].kind in _FUSABLE and not (
                ops[j].kind == "map_batches" and ops[j].compute == "actors"
            ):
                j += 1
            rest = _chain_spec(ops[i + 1 : j])
            n_actors = op.concurrency or 2
            actors = [_MapActor.remote(op.fn, op.fn_constructor_args, rest)
                      for _ in range(n_actors)]
            rr = [0]

            def submit(r, _op=op, _actors=actors, _rr=rr):
                a = _actors[_rr[0] % len(_actors)]
                _rr[0] += 1
                return a.apply.remote(_op.batch_size, _op.batch_format,
                                      _op.fn_args, _op.fn_kwargs, r)

            def actor_stage(up, _actors=actors, _n=n_actors):
                inner = _windowed(submit, up, max(2, 2 * _n))
                try:
                    yield from inner
                finally:
                    # Drain in-flight calls, then release the leased
                    # workers — actors would otherwise pin CPUs forever.
                    for a in _actors:
                        try:
                            ray_tpu.kill(a)
                        # tpulint: allow(broad-except reason=stage teardown; an actor that already died released its lease, which is all kill is for here)
                        except Exception:  # noqa: BLE001
                            pass

            stream = actor_stage(stream)
            i = j
            continue
        # ---- all-to-all / terminal ops materialize upstream refs
        if op.kind == "repartition":
            refs = list(stream)
            counts = _counts(refs)
            plans = _slice_plan(counts, op.n)
            outs = []
            for pl in plans:
                order = sorted({t[0] for t in pl})
                outs.append(_slice_concat.remote(_localize(pl), *[refs[k] for k in order]))
            stream = iter(outs)
        elif op.kind == "random_shuffle":
            refs = list(stream)
            n = op.n_out or ctx.shuffle_partitions or len(refs) or 1
            if op.seed is not None:
                base = op.seed
            else:  # fresh order every execution, like an unseeded shuffle
                import os as _os

                base = int.from_bytes(_os.urandom(4), "little")
            mapped = [_shuffle_map.options(num_returns=n).remote(n, base + mi, r)
                      for mi, r in enumerate(refs)]
            mapped = [m if isinstance(m, list) else [m] for m in mapped]
            stream = iter([
                _shuffle_reduce.remote(base ^ (j + 1), *[m[j] for m in mapped])
                for j in range(n)
            ])
        elif op.kind == "sort":
            refs = list(stream)
            n = len(refs) or 1
            samples = ray_tpu.get([_sample_keys.remote(op.key, 20, r) for r in refs])
            allkeys = np.sort(np.concatenate([s for s in samples if len(s)]) if any(
                len(s) for s in samples) else np.array([]))
            if len(allkeys) and n > 1:
                bidx = np.linspace(0, len(allkeys) - 1, num=n + 1).astype(int)[1:-1]
                boundaries = allkeys[bidx]
            else:
                boundaries = allkeys[:0]
            nparts = len(boundaries) + 1
            mapped = [_range_part.options(num_returns=nparts).remote(op.key, boundaries, r)
                      for r in refs]
            mapped = [m if isinstance(m, list) else [m] for m in mapped]
            out = [_merge_sorted.remote(op.key, op.descending, *[m[j] for m in mapped])
                   for j in range(nparts)]
            stream = iter(out[::-1] if op.descending else out)
        elif op.kind == "limit":
            stream = _limit_stream(stream, op.n)
        elif op.kind == "union":
            streams = [stream] + [execute(p, ctx) for p in op.others]
            stream = (r for s in streams for r in s)
        elif op.kind == "zip":
            refs = list(stream)
            rrefs = list(execute(op.other, ctx))
            lcounts, rcounts = _counts(refs), _counts(rrefs)
            if sum(lcounts) != sum(rcounts):
                raise ValueError("zip requires equal row counts "
                                 f"({sum(lcounts)} vs {sum(rcounts)})")
            plans = _row_align(lcounts, rcounts)
            stream = iter([
                _zip_blocks.remote(_localize(pl), refs[li],
                                   *[rrefs[k] for k in sorted({t[0] for t in pl})])
                for li, pl in enumerate(plans)
            ])
        elif op.kind == "join":
            # Hash join (reference: hash-shuffle join operators,
            # data/_internal/execution/operators/hash_shuffle.py):
            # both sides hash-partition on the key; partition j of the
            # left joins partition j of the right.
            lrefs = list(stream)
            rrefs = list(execute(op.other, ctx))
            n = op.n_out or max(min(len(lrefs) + len(rrefs), 8), 1)
            # Side schemas travel to every partition so a block whose
            # partition got rows from only ONE side still emits (and
            # null-fills) the other side's columns.
            lschema = _first_schema(lrefs)
            rschema = _first_schema(rrefs)
            lmap = [_hash_part.options(num_returns=n).remote(op.on, n, r)
                    for r in lrefs]
            rmap = [_hash_part.options(num_returns=n).remote(op.on, n, r)
                    for r in rrefs]
            lmap = [m if isinstance(m, list) else [m] for m in lmap]
            rmap = [m if isinstance(m, list) else [m] for m in rmap]
            stream = iter([
                _hash_join.remote(
                    op.on, op.how, op.suffix, lschema, rschema, len(lmap),
                    *[m[j] for m in lmap], *[m[j] for m in rmap],
                )
                for j in range(n)
            ])
        elif op.kind in ("aggregate", "map_groups"):
            refs = list(stream)
            if op.kind == "aggregate" and op.key is None:
                partials = [_agg_partition.remote(None, op.aggs, r) for r in refs]
                stream = iter([_combine_global.remote(op.aggs, *partials)])
            else:
                n = op.n_out or min(len(refs), 8) or 1
                mapped = [_hash_part.options(num_returns=n).remote(op.key, n, r)
                          for r in refs]
                mapped = [m if isinstance(m, list) else [m] for m in mapped]
                if op.kind == "aggregate":
                    stream = iter([
                        _agg_partition.remote(op.key, op.aggs, *[m[j] for m in mapped])
                        for j in range(n)
                    ])
                else:
                    stream = iter([
                        _map_groups.remote(op.key, op.fn, op.batch_format,
                                           *[m[j] for m in mapped])
                        for j in range(n)
                    ])
        else:
            raise NotImplementedError(op.kind)
        i += 1
    return stream


@ray_tpu.remote
def _block_schema(blk):
    blk = B.ensure_numpy(blk)
    return {c: str(blk[c].dtype) for c in blk}


def _first_schema(refs) -> dict:
    """{col: dtype str} from the first non-empty block of a ref list.
    Probes one block at a time — most datasets answer on the first."""
    for r in refs:
        schema = ray_tpu.get(_block_schema.remote(r))
        if schema:
            return schema
    return {}


def _join_fill(dtype, n: int) -> np.ndarray:
    """Null-fill column for unmatched join rows: NaN for numerics
    (ints promote to float), None objects otherwise."""
    dtype = np.dtype(dtype)
    if np.issubdtype(dtype, np.number):
        return np.full(n, np.nan)
    out = np.empty(n, dtype=object)
    out[:] = None
    return out


@ray_tpu.remote
def _hash_join(on, how, suffix, lschema, rschema, n_left, *parts):
    # Join inputs can arrive as Arrow tables (direct joins without a
    # repartition pass); the kernel does numpy column math throughout.
    parts = tuple(B.ensure_numpy(p) for p in parts)
    left = [p for p in parts[:n_left] if p]
    right = [p for p in parts[n_left:] if p]
    left = B.concat(left) if left else {}
    right = B.concat(right) if right else {}
    if not left and not right:
        return {}

    index: dict = {}
    n_right = B.num_rows(right) if right else 0
    if right:
        for j, k in enumerate(right[on].tolist()):
            index.setdefault(k, []).append(j)
    li: list[int] = []
    ri: list[int] = []
    left_unmatched: list[int] = []
    matched_right: set = set()
    if left:
        for i, k in enumerate(left[on].tolist()):
            hits = index.get(k)
            if hits:
                for j in hits:
                    li.append(i)
                    ri.append(j)
                    matched_right.add(j)
            else:
                left_unmatched.append(i)
    if how not in ("left", "outer"):
        left_unmatched = []
    right_unmatched = (
        [j for j in range(n_right) if j not in matched_right]
        if how in ("right", "outer")
        else []
    )

    li_a = np.asarray(li, dtype=np.int64)
    ri_a = np.asarray(ri, dtype=np.int64)
    lu_a = np.asarray(left_unmatched, dtype=np.int64)
    ru_a = np.asarray(right_unmatched, dtype=np.int64)

    out: dict = {}
    # Key column: sourced from whichever side each row group came from.
    key_parts = []
    if left:
        key_parts += [left[on][li_a], left[on][lu_a]]
    if right and len(ru_a):
        key_parts.append(right[on][ru_a])
    out[on] = (
        np.concatenate(key_parts) if key_parts else np.array([])
    )
    # Schemas (not this partition's blocks) define the column set, so a
    # one-sided partition still emits the other side's columns as nulls.
    left_cols = [c for c in lschema if c != on]
    right_cols = [c for c in rschema if c != on]
    n_matched = len(li_a)
    for c in left_cols:
        if left:
            col = left[c]
            out[c] = np.concatenate(
                [col[li_a], col[lu_a], _join_fill(col.dtype, len(ru_a))]
            )
        else:
            out[c] = _join_fill(lschema[c], n_matched + len(lu_a) + len(ru_a))
    for c in right_cols:
        name = f"{c}{suffix}" if c in lschema else c
        if right:
            col = right[c]
            out[name] = np.concatenate(
                [col[ri_a], _join_fill(col.dtype, len(lu_a)), col[ru_a]]
            )
        else:
            out[name] = _join_fill(
                rschema[c], n_matched + len(lu_a) + len(ru_a)
            )
    return out


@ray_tpu.remote
def _combine_global(aggs, *partials):
    blk = B.concat([p for p in partials if p])
    row = {}
    for kind, col, out in aggs:
        vals = blk[out]
        if kind == "count":
            row[out] = np.sum(vals)
        elif kind == "mean":
            row[out] = np.mean(vals)  # exact only for equal partitions; partial means
        elif kind in ("sum", "min", "max"):
            row[out] = _agg_one(kind, vals)
        else:
            row[out] = _agg_one(kind, vals)
    return B.from_rows([row])


def _localize(pl: list[tuple]) -> list[tuple]:
    """Rewrite input indices in a slice plan to positional arg indices."""
    order = sorted({t[0] for t in pl})
    remap = {k: i for i, k in enumerate(order)}
    return [(remap[i], s, e) for i, s, e in pl]


def _row_align(lcounts: list[int], rcounts: list[int]) -> list[list[tuple]]:
    """For each left block, the (right_idx, start, end) ranges covering the
    same global rows."""
    plans = []
    roffsets = np.cumsum([0] + rcounts)
    goff = 0
    for lc in lcounts:
        lo, hi = goff, goff + lc
        pl = []
        for ri in range(len(rcounts)):
            rlo, rhi = roffsets[ri], roffsets[ri + 1]
            a, b = max(lo, rlo), min(hi, rhi)
            if a < b:
                pl.append((ri, int(a - rlo), int(b - rlo)))
        plans.append(pl)
        goff = hi
    return plans


def _limit_stream(stream: Iterator, n: int) -> Iterator:
    remaining = n
    for ref in stream:
        if remaining <= 0:
            return
        cnt = ray_tpu.get(_count_rows.remote(ref))
        if cnt <= remaining:
            remaining -= cnt
            yield ref
        else:
            yield _head.remote(ref, remaining)
            remaining = 0
