"""ray_tpu.data: distributed datasets with streaming execution.

Capability-equivalent of the reference's Data library (reference:
python/ray/data/ — lazy logical plan, streaming executor over blocks in
the object store, datasources, groupby/shuffle/sort, Train integration),
re-based on columnar-numpy blocks that feed JAX input pipelines without
conversion.
"""

from __future__ import annotations

from ray_tpu.data import block, datasource
from ray_tpu.data.dataset import DataIterator, Dataset, GroupedData, MaterializedDataset
from ray_tpu.data.executor import DataContext
from ray_tpu.data.plan import LogicalPlan, Read


def _from_read_tasks(tasks) -> Dataset:
    return Dataset(LogicalPlan([Read(tasks)]))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    if parallelism <= 0:
        parallelism = min(200, max(1, n // 1000 or 1))
    return _from_read_tasks(datasource.range_tasks(n, parallelism))


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = min(200, max(1, len(items) // 100 or 1))
    return _from_read_tasks(datasource.items_tasks(list(items), parallelism))


def from_numpy(arr, *, parallelism: int = 4) -> Dataset:
    import numpy as np

    chunks = np.array_split(arr, max(1, parallelism))
    return from_blocks([{"data": c} for c in chunks])


def from_blocks(blocks: list) -> Dataset:
    import ray_tpu

    refs = [ray_tpu.put(b) for b in blocks]
    return MaterializedDataset(refs)


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return from_blocks([block.from_pandas(df) for df in dfs])


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return from_blocks([block.from_arrow(t) for t in tables])


def read_parquet(paths, *, columns=None) -> Dataset:
    return _from_read_tasks(datasource.file_tasks(paths, "parquet", columns=columns))


def read_csv(paths) -> Dataset:
    return _from_read_tasks(datasource.file_tasks(paths, "csv"))


def read_json(paths) -> Dataset:
    return _from_read_tasks(datasource.file_tasks(paths, "json"))


def read_text(paths) -> Dataset:
    return _from_read_tasks(datasource.file_tasks(paths, "text"))


def read_numpy(paths) -> Dataset:
    return _from_read_tasks(datasource.file_tasks(paths, "numpy"))


def read_delta(table: str, *, columns=None) -> Dataset:
    """Read a Delta Lake table (parquet + JSON transaction log): one
    read task per active data file, partition values as columns
    (data/delta.py; reference surface: ray.data lakehouse
    datasources)."""
    from ray_tpu.data import delta

    return _from_read_tasks(delta.delta_tasks(table, columns=columns))


def read_bigquery(
    *,
    project: str,
    query: str | None = None,
    dataset: str | None = None,
    transport=None,
) -> Dataset:
    """Read BigQuery rows over the REST v2 API (data/bigquery.py;
    reference: python/ray/data read_bigquery). ``dataset`` is
    "dataset.table" sugar for a full-table SELECT. ``transport``
    injects a recorded transport in tests (zero-egress CI), exactly
    like the GKE provider's fixtures."""
    from ray_tpu.data import bigquery

    return _from_read_tasks(
        bigquery.bigquery_tasks(
            project=project, query=query, dataset=dataset,
            transport=transport,
        )
    )


__all__ = [
    "Dataset", "MaterializedDataset", "GroupedData", "DataIterator",
    "DataContext", "range", "from_items", "from_blocks", "from_pandas",
    "from_arrow", "from_numpy", "read_parquet", "read_csv", "read_json",
    "read_text", "read_numpy", "read_delta", "read_bigquery",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('data')
del _rlu
