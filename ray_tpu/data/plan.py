"""Logical plan: a linear chain of operators over blocks.

Mirrors the reference's logical-plan layer (reference:
python/ray/data/_internal/logical/interfaces/logical_plan.py) in reduced
form: a `LogicalPlan` is a list of `Op` records; the streaming executor
(executor.py) turns each into a physical generator stage.
"""

from __future__ import annotations

from typing import Any, Callable


class Op:
    kind: str = ""

    def name(self) -> str:
        return self.kind


class Read(Op):
    kind = "read"

    def __init__(self, tasks: list, schema_hint=None):
        self.tasks = tasks  # list[ReadTask]


class RefSource(Op):
    """Source over already-materialized block refs (MaterializedDataset)."""

    kind = "ref_source"

    def __init__(self, refs: list):
        self.refs = refs


class MapBatches(Op):
    kind = "map_batches"

    def __init__(self, fn, *, batch_size=None, batch_format="numpy",
                 fn_args=(), fn_kwargs=None, concurrency=None, compute="tasks",
                 fn_constructor_args=()):
        self.fn = fn
        self.batch_size = batch_size
        self.batch_format = batch_format
        self.fn_args = tuple(fn_args)
        self.fn_kwargs = dict(fn_kwargs or {})
        self.concurrency = concurrency
        self.compute = compute  # "tasks" | "actors" (callable-class fns)
        self.fn_constructor_args = tuple(fn_constructor_args)


class MapRows(Op):
    kind = "map"

    def __init__(self, fn):
        self.fn = fn


class Filter(Op):
    kind = "filter"

    def __init__(self, fn):
        self.fn = fn


class FlatMap(Op):
    kind = "flat_map"

    def __init__(self, fn):
        self.fn = fn


class AddColumn(Op):
    kind = "add_column"

    def __init__(self, name: str, fn):
        self.col_name = name
        self.fn = fn


class DropColumns(Op):
    kind = "drop_columns"

    def __init__(self, cols: list[str]):
        self.cols = list(cols)


class SelectColumns(Op):
    kind = "select_columns"

    def __init__(self, cols: list[str]):
        self.cols = list(cols)


class Repartition(Op):
    kind = "repartition"

    def __init__(self, n: int):
        self.n = n


class RandomShuffle(Op):
    kind = "random_shuffle"

    def __init__(self, seed=None, n_out=None):
        self.seed = seed
        self.n_out = n_out


class Sort(Op):
    kind = "sort"

    def __init__(self, key: str, descending: bool = False):
        self.key = key
        self.descending = descending


class Limit(Op):
    kind = "limit"

    def __init__(self, n: int):
        self.n = n


class Union(Op):
    kind = "union"

    def __init__(self, others: list):
        self.others = others  # list[LogicalPlan]


class Zip(Op):
    kind = "zip"

    def __init__(self, other):
        self.other = other  # LogicalPlan


class Join(Op):
    kind = "join"

    def __init__(self, other, on: str, how: str = "inner", n_out=None,
                 suffix: str = "_r"):
        self.other = other  # the right side's LogicalPlan
        self.on = on
        self.how = how
        self.n_out = n_out
        self.suffix = suffix


class GroupByAggregate(Op):
    kind = "aggregate"

    def __init__(self, key: str | None, aggs: list, n_out=None):
        self.key = key
        self.aggs = aggs  # list[(agg_kind, column, out_name)]
        self.n_out = n_out


class MapGroups(Op):
    kind = "map_groups"

    def __init__(self, key: str, fn, batch_format="numpy", n_out=None):
        self.key = key
        self.fn = fn
        self.batch_format = batch_format
        self.n_out = n_out


class LogicalPlan:
    def __init__(self, ops: list[Op] | None = None):
        self.ops: list[Op] = list(ops or [])

    def with_op(self, op: Op) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def describe(self) -> str:
        return " -> ".join(op.name() for op in self.ops)
