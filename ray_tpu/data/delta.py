"""Delta Lake table reading (and a minimal writer for round-trips).

Reference surface: ray.data's lakehouse datasources
(python/ray/data/_internal/datasource/ — delta sharing, iceberg,
lance). Delta is the one fully implementable with this image's stack:
the table format is parquet data files plus a JSON transaction log
(`_delta_log/<version>.json`, optional parquet checkpoints), no avro.

Read path (the delta protocol's client rules):
- find the latest checkpoint from ``_delta_log/_last_checkpoint`` (or
  scan), seed the active-file set from its `add` records,
- apply newer JSON commits in version order: each line holds one
  action — ``add`` (file joins the table), ``remove`` (file leaves),
  ``metaData`` (schema + partition columns), ``protocol``/
  ``commitInfo`` (ignored for reads),
- one ReadTask per surviving data file; Hive-style partition values
  from ``add.partitionValues`` come back as columns, cast per the
  table schema.

The writer emits a spec-shaped single-commit table (data parquet +
00000000000000000000.json with protocol/metaData/add actions) — enough
for round-trip tests and for handing small tables to real Delta
readers.
"""

from __future__ import annotations

import json
import os
import uuid
from typing import Any

from ray_tpu.data import block as B

_LOG_DIR = "_delta_log"
_VERSION_DIGITS = 20


def _log_path(table: str, version: int) -> str:
    return os.path.join(
        table, _LOG_DIR, f"{version:0{_VERSION_DIGITS}d}.json"
    )


def _parse_schema_types(schema_string: str) -> "dict[str, str]":
    """Spark-JSON schema → {column: primitive type name}."""
    try:
        schema = json.loads(schema_string)
    except (TypeError, ValueError):
        return {}
    out = {}
    for field in schema.get("fields", []):
        t = field.get("type")
        if isinstance(t, str):
            out[field.get("name", "")] = t
    return out


def _cast_partition(value: "str | None", typ: str):
    if value is None:
        return None
    if typ in ("integer", "long", "short", "byte"):
        return int(value)
    if typ in ("double", "float"):
        return float(value)
    if typ == "boolean":
        return value.lower() == "true"
    return value


class DeltaSnapshot:
    """Resolved table state: active files + schema metadata."""

    def __init__(self, table: str):
        self.table = table
        log_dir = os.path.join(table, _LOG_DIR)
        if not os.path.isdir(log_dir):
            raise FileNotFoundError(
                f"{table!r} is not a Delta table (no {_LOG_DIR}/)"
            )
        entries = sorted(os.listdir(log_dir))
        commits = [
            e for e in entries
            if e.endswith(".json") and e[:_VERSION_DIGITS].isdigit()
        ]
        self.active: dict[str, dict] = {}  # path -> add action
        self.partition_columns: list[str] = []
        self.schema_types: dict[str, str] = {}
        start_version = 0
        cp_version, cp_parts = self._checkpoint_ref(log_dir, entries)
        if cp_version is not None:
            start_version = cp_version + 1
            for part in cp_parts:
                self._apply_checkpoint(os.path.join(log_dir, part))
        for name in commits:
            if int(name[:_VERSION_DIGITS]) < start_version:
                continue
            with open(os.path.join(log_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        self._apply_action(json.loads(line))
        self.version = (
            int(commits[-1][:_VERSION_DIGITS]) if commits
            else start_version - 1
        )

    @staticmethod
    def _checkpoint_ref(log_dir, entries):
        """Latest checkpoint version + its part files. Prefers the
        ``_last_checkpoint`` pointer (the spec's fast path); falls back
        to scanning for both single-part (<v>.checkpoint.parquet) and
        multi-part (<v>.checkpoint.<i>.<n>.parquet) names."""
        import re

        pointer = os.path.join(log_dir, "_last_checkpoint")
        by_version: dict[int, list[str]] = {}
        pat = re.compile(
            rf"^(\d{{{_VERSION_DIGITS}}})\.checkpoint"
            r"(?:\.\d+\.\d+)?\.parquet$"
        )
        for e in entries:
            m = pat.match(e)
            if m:
                by_version.setdefault(int(m.group(1)), []).append(e)
        if os.path.exists(pointer):
            try:
                with open(pointer) as f:
                    ref = json.load(f)
                v = int(ref["version"])
                parts = by_version.get(v)
                if parts and len(parts) == int(ref.get("parts", 1)):
                    return v, sorted(parts)
            except (OSError, ValueError, KeyError):
                pass  # corrupt pointer: trust the scan instead
        if by_version:
            v = max(by_version)
            return v, sorted(by_version[v])
        return None, []

    def _apply_checkpoint(self, path: str) -> None:
        import pyarrow.parquet as pq

        tbl = pq.read_table(path)
        for row in tbl.to_pylist():
            for kind in ("add", "remove", "metaData"):
                if row.get(kind):
                    self._apply_action({kind: row[kind]})

    def _apply_action(self, action: dict) -> None:
        if "metaData" in action and action["metaData"]:
            md = action["metaData"]
            self.partition_columns = list(
                md.get("partitionColumns", [])
            )
            self.schema_types = _parse_schema_types(
                md.get("schemaString", "")
            )
        elif "add" in action and action["add"]:
            add = action["add"]
            self.active[add["path"]] = add
        elif "remove" in action and action["remove"]:
            self.active.pop(action["remove"]["path"], None)

    def files(self) -> "list[dict]":
        return [self.active[p] for p in sorted(self.active)]


class _DeltaFileRead:
    """One active data file → one block, partition values attached."""

    def __init__(self, table, add, partition_columns, schema_types,
                 columns=None):
        self.table = table
        self.add = add
        self.partition_columns = partition_columns
        self.schema_types = schema_types
        self.columns = columns

    def __call__(self) -> B.Block:
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = os.path.join(self.table, self.add["path"])
        file_cols = None
        if self.columns is not None:
            file_cols = [
                c for c in self.columns
                if c not in self.partition_columns
            ]
        tbl = pq.read_table(path, columns=file_cols)
        pv = self.add.get("partitionValues", {})
        for col in self.partition_columns:
            if self.columns is not None and col not in self.columns:
                continue
            value = _cast_partition(
                pv.get(col), self.schema_types.get(col, "string")
            )
            tbl = tbl.append_column(
                col, pa.array([value] * tbl.num_rows)
            )
        return B.from_arrow(tbl)


def delta_tasks(table: str, *, columns=None) -> list:
    snap = DeltaSnapshot(table)
    return [
        _DeltaFileRead(
            table, add, snap.partition_columns, snap.schema_types,
            columns=columns,
        )
        for add in snap.files()
    ] or [lambda: {}]


def _spark_type(np_dtype) -> str:
    import numpy as np

    if np.issubdtype(np_dtype, np.bool_):
        return "boolean"
    if np.issubdtype(np_dtype, np.integer):
        return "long"
    if np.issubdtype(np_dtype, np.floating):
        return "double"
    return "string"


def _block_columns(blk) -> "dict[str, Any]":
    """Block (arrow Table or dict of ndarrays) → {name: ndarray}."""
    import numpy as np

    if isinstance(blk, dict):
        return {k: np.asarray(v) for k, v in blk.items()}
    return {
        name: blk.column(name).to_numpy(zero_copy_only=False)
        for name in blk.schema.names
    }


def write_delta(ds, table: str, *, partition_by: "str | None" = None):
    """Write a Dataset as a NEW single-commit Delta table (errors if
    the table exists — this is a test/export surface, not a
    transactional writer)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    log_dir = os.path.join(table, _LOG_DIR)
    if os.path.exists(log_dir):
        raise FileExistsError(f"delta table {table!r} already exists")
    os.makedirs(log_dir)
    blocks = [
        _block_columns(b) for b in ds.iter_blocks() if B.num_rows(b)
    ]
    if not blocks:
        raise ValueError("cannot write an empty delta table")
    fields = [
        {
            "name": name,
            "type": _spark_type(arr.dtype),
            "nullable": True,
            "metadata": {},
        }
        for name, arr in blocks[0].items()
    ]
    schema_string = json.dumps(
        {"type": "struct", "fields": fields}
    )
    adds = []
    for i, blk in enumerate(blocks):
        parts: "dict[Any, dict]" = {}
        if partition_by is None:
            parts[None] = blk
        else:
            col = blk[partition_by]
            for v in np.unique(col):
                mask = col == v
                parts[v.item() if hasattr(v, "item") else v] = {
                    name: arr[mask]
                    for name, arr in blk.items()
                    if name != partition_by
                }
        for pv, part in parts.items():
            if partition_by is None:
                rel = f"part-{i:05d}-{uuid.uuid4().hex[:8]}.parquet"
            else:
                rel = (
                    f"{partition_by}={pv}/part-{i:05d}-"
                    f"{uuid.uuid4().hex[:8]}.parquet"
                )
                os.makedirs(
                    os.path.join(table, os.path.dirname(rel)),
                    exist_ok=True,
                )
            pq.write_table(
                pa.table(part), os.path.join(table, rel)
            )
            adds.append(
                {
                    "add": {
                        "path": rel,
                        "partitionValues": (
                            {} if partition_by is None
                            else {partition_by: str(pv)}
                        ),
                        "size": os.path.getsize(
                            os.path.join(table, rel)
                        ),
                        "modificationTime": 0,
                        "dataChange": True,
                    }
                }
            )
    actions = [
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        {
            "metaData": {
                "id": str(uuid.uuid4()),
                "format": {"provider": "parquet", "options": {}},
                "schemaString": schema_string,
                "partitionColumns": (
                    [partition_by] if partition_by else []
                ),
                "configuration": {},
            }
        },
        *adds,
    ]
    with open(_log_path(table, 0), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")
