"""Arrow-native blocks: pyarrow Tables as first-class dataset blocks,
plus the tensor extension type for multi-dimensional columns.

Reference: python/ray/data/_internal/arrow_block.py:213
``ArrowBlockAccessor`` (the reference's canonical block IS an Arrow
table) and python/ray/air/util/tensor_extensions/arrow.py
``ArrowTensorType``/``ArrowTensorArray`` (fixed-shape ndarrays stored
as FixedSizeList with shape metadata, parquet round-trip included).

TPU-native stance: the CANONICAL compute block stays a numpy column
dict — that is the zero-copy host format JAX feeding wants — but
Arrow tables now flow through the pipeline natively: ``from_arrow``
and the parquet/CSV scans keep the table (no eager numpy copy),
streaming ops that only move rows (slice/take/concat/limit/
repartition/iter_batches) execute on Arrow zero-copy, and
``to_batch(..., "pyarrow")`` hands the table straight to the user.
Ops that do column math (sort/groupby/join/zip/add_column) normalize
to numpy at their kernel entry via ``block.ensure_numpy`` — one
conversion, at the edge where the math happens.
"""

from __future__ import annotations

import json

import numpy as np
import pyarrow as pa

_TENSOR_EXT_NAME = "ray_tpu.tensor"


class ArrowTensorType(pa.ExtensionType):
    """Fixed-shape tensor column: each row is an ndarray of ``shape``,
    stored as FixedSizeList(value_type, prod(shape)) so any Arrow
    consumer (and parquet) can read the flat data; the shape rides in
    the serialized metadata (reference: ArrowTensorType, air/util/
    tensor_extensions/arrow.py)."""

    def __init__(self, shape: tuple, value_type: pa.DataType):
        self._shape = tuple(int(s) for s in shape)
        size = int(np.prod(self._shape)) if self._shape else 1
        super().__init__(
            pa.list_(value_type, size), _TENSOR_EXT_NAME
        )

    @property
    def shape(self) -> tuple:
        return self._shape

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps({"shape": list(self._shape)}).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        shape = tuple(json.loads(serialized.decode())["shape"])
        return cls(shape, storage_type.value_type)

    def __arrow_ext_class__(self):
        return ArrowTensorArray


class ArrowTensorArray(pa.ExtensionArray):
    """Array of fixed-shape tensors; ``to_numpy`` reshapes the flat
    storage zero-copy when the buffer layout allows."""

    @staticmethod
    def from_numpy(arr: np.ndarray) -> "ArrowTensorArray":
        if arr.ndim < 2:
            raise ValueError("tensor columns need ndim >= 2")
        n = arr.shape[0]
        shape = arr.shape[1:]
        flat = np.ascontiguousarray(arr).reshape(n, -1)
        value_type = pa.from_numpy_dtype(arr.dtype)
        typ = ArrowTensorType(shape, value_type)
        storage = pa.FixedSizeListArray.from_arrays(
            pa.array(flat.reshape(-1), type=value_type), flat.shape[1]
        )
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy(self, zero_copy_only: bool = False) -> np.ndarray:
        typ: ArrowTensorType = self.type
        flat = self.storage.flatten().to_numpy(
            zero_copy_only=zero_copy_only
        )
        return flat.reshape((len(self),) + typ.shape)


def _register():
    try:
        pa.register_extension_type(
            ArrowTensorType((1,), pa.float32())
        )
    except pa.ArrowKeyError:
        pass  # already registered (re-import)


_register()


# ------------------------------------------------------------ conversion


def table_from_numpy_dict(block: dict) -> pa.Table:
    """numpy column dict → Arrow table; ndim>=2 columns become tensor
    extension columns, object columns fall back to python lists."""
    cols = {}
    for name, arr in block.items():
        arr = np.asarray(arr)
        if arr.ndim >= 2:
            cols[name] = ArrowTensorArray.from_numpy(arr)
        elif arr.dtype == object:
            cols[name] = pa.array(list(arr))
        else:
            cols[name] = pa.array(arr)
    return pa.table(cols)


def numpy_dict_from_table(table: pa.Table) -> dict:
    """Arrow table → numpy column dict (the JAX feeding format).
    Tensor extension columns come back as ndarrays with their original
    shape; plain columns convert zero-copy where Arrow allows."""
    out = {}
    for name, col in zip(table.column_names, table.columns):
        col = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        # ArrowTensorArray.to_numpy reshapes via its override; plain
        # columns convert directly — one call covers both.
        out[name] = col.to_numpy(zero_copy_only=False)
    return out


# -------------------------------------------------------------- accessor


def is_arrow_block(block) -> bool:
    return isinstance(block, pa.Table)


def num_rows(table: pa.Table) -> int:
    return table.num_rows


def size_bytes(table: pa.Table) -> int:
    return table.nbytes


def schema(table: pa.Table) -> dict:
    return {
        name: typ for name, typ in zip(table.schema.names, table.schema.types)
    }


def slice_table(table: pa.Table, start: int, end: int) -> pa.Table:
    """Zero-copy: Arrow slices share buffers."""
    start = max(0, start)
    return table.slice(start, max(0, min(end, table.num_rows) - start))


def take_table(table: pa.Table, idx: np.ndarray) -> pa.Table:
    return table.take(pa.array(np.asarray(idx, dtype=np.int64)))


def concat_tables(tables: list) -> pa.Table:
    return pa.concat_tables([t for t in tables if t.num_rows > 0])


def to_rows(table: pa.Table):
    # Batchwise so a multi-GB table never materializes a full
    # list-of-dicts copy up front.
    for batch in table.to_batches():
        yield from batch.to_pylist()
