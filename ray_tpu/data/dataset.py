"""Dataset: the lazy, distributed user-facing API.

Mirrors the reference's Dataset surface (reference:
python/ray/data/dataset.py — map_batches, filter, random_shuffle, sort,
groupby, iter_batches :5432, streaming_split for Train integration) over
the reduced logical plan + streaming executor in this package.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data import block as B
from ray_tpu.data import plan as P
from ray_tpu.data.executor import DataContext, execute


class Dataset:
    def __init__(self, plan: P.LogicalPlan):
        self._plan = plan

    # ------------------------------------------------------------ lazy ops
    def _with(self, op: P.Op) -> "Dataset":
        return Dataset(self._plan.with_op(op))

    def map_batches(self, fn, *, batch_size=None, batch_format="numpy",
                    fn_args=(), fn_kwargs=None, concurrency=None,
                    compute=None, fn_constructor_args=()) -> "Dataset":
        is_class = isinstance(fn, type)
        return self._with(P.MapBatches(
            fn, batch_size=batch_size, batch_format=batch_format,
            fn_args=fn_args, fn_kwargs=fn_kwargs, concurrency=concurrency,
            compute=compute or ("actors" if is_class else "tasks"),
            fn_constructor_args=fn_constructor_args))

    def map(self, fn) -> "Dataset":
        return self._with(P.MapRows(fn))

    def filter(self, fn) -> "Dataset":
        return self._with(P.Filter(fn))

    def flat_map(self, fn) -> "Dataset":
        return self._with(P.FlatMap(fn))

    def add_column(self, name: str, fn) -> "Dataset":
        return self._with(P.AddColumn(name, fn))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        return self._with(P.DropColumns(cols))

    def select_columns(self, cols: list[str]) -> "Dataset":
        return self._with(P.SelectColumns(cols))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(P.Repartition(num_blocks))

    def random_shuffle(self, *, seed=None) -> "Dataset":
        return self._with(P.RandomShuffle(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(P.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._with(P.Limit(n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(P.Union([o._plan for o in others]))

    def join(
        self,
        other: "Dataset",
        on: str,
        how: str = "inner",
        *,
        num_partitions: int | None = None,
        suffix: str = "_r",
    ) -> "Dataset":
        """Hash join on a key column (reference: the hash-shuffle join
        operator, python/ray/data/_internal/execution/operators/join.py /
        hash_shuffle.py). ``how``: inner | left | right | outer.
        Overlapping non-key columns from the right side get ``suffix``."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join type {how!r}")
        return self._with(
            P.Join(other._plan, on, how, n_out=num_partitions, suffix=suffix)
        )

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(P.Zip(other._plan))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ------------------------------------------------------- aggregations
    def _global_agg(self, *specs: tuple):
        """One aggregation pass over the plan for all (kind, on) specs."""
        aggs = [(k, on, k if on is None else f"{k}({on})") for k, on in specs]
        refs = list(execute(self._plan.with_op(
            P.GroupByAggregate(None, aggs))))
        blocks = ray_tpu.get(refs)
        blk = B.concat([b for b in blocks if b])
        if not B.num_rows(blk):
            return [None] * len(aggs)
        return [blk[out][0] for _, _, out in aggs]

    def count(self) -> int:
        from ray_tpu.data.executor import _count_rows

        refs = list(execute(self._plan))
        return int(sum(ray_tpu.get([_count_rows.remote(r) for r in refs])))

    def sum(self, on: str):
        return self._global_agg(("sum", on))[0]

    def min(self, on: str):
        return self._global_agg(("min", on))[0]

    def max(self, on: str):
        return self._global_agg(("max", on))[0]

    def mean(self, on: str):
        # exact sum/count in ONE pass over the plan (partition-mean
        # averaging would be biased; two passes would double the work)
        total, n = self._global_agg(("sum", on), ("count", on))
        return total / n if n else None

    # ------------------------------------------------------- consumption
    def materialize(self) -> "MaterializedDataset":
        refs = list(execute(self._plan))
        return MaterializedDataset(refs)

    def iter_blocks(self) -> Iterator[B.Block]:
        for ref in execute(self._plan):
            yield ray_tpu.get(ref)

    def iter_rows(self) -> Iterator[dict]:
        for blk in self.iter_blocks():
            yield from B.to_rows(blk)

    def iter_batches(self, *, batch_size: int = 256, batch_format="numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: int | None = None,
                     local_shuffle_seed=None) -> Iterator:
        """Rebatch the block stream (reference: dataset.py:5432 iter_batches
        → block_batching); optional local shuffle buffer mirrors
        LocalShuffleBuffer semantics."""
        buf: list[B.Block] = []
        buffered = 0
        rng = np.random.default_rng(local_shuffle_seed)
        lo = local_shuffle_buffer_size or 0

        def drain(min_rows: int):
            nonlocal buf, buffered
            while buffered >= max(batch_size, min_rows) and buffered >= batch_size:
                blk = B.concat(buf)
                if lo:
                    blk = B.take_idx(blk, rng.permutation(B.num_rows(blk)))
                out = B.slice_block(blk, 0, batch_size)
                rest = B.slice_block(blk, batch_size, B.num_rows(blk))
                buf = [rest] if B.num_rows(rest) else []
                buffered = B.num_rows(rest)
                yield B.to_batch(out, batch_format)
                if lo and buffered < lo:
                    return

        for blk in self.iter_blocks():
            if B.num_rows(blk) == 0:
                continue
            buf.append(blk)
            buffered += B.num_rows(blk)
            yield from drain(lo)
        while buffered >= batch_size:
            yield from drain(0)
            if buffered < batch_size:
                break
        if buffered and not drop_last:
            yield B.to_batch(B.concat(buf), batch_format)

    def take(self, n: int = 20) -> list[dict]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def to_pandas(self):
        return B.to_pandas(B.concat(list(self.iter_blocks())))

    def to_arrow(self):
        """Materialize as one pyarrow Table; ndim>=2 numpy columns
        become tensor extension columns (reference: Dataset.to_arrow_refs,
        data/dataset.py — block-level tables concatenated here since
        the driver already holds the refs)."""
        return B.to_arrow(B.concat(list(self.iter_blocks())))

    def schema(self) -> dict:
        for blk in self.iter_blocks():
            if B.num_rows(blk):
                return B.schema(blk)
        return {}

    def num_blocks(self) -> int:
        return len(list(execute(self._plan)))

    # ------------------------------------------------- Train integration
    def split(self, n: int, *, equal: bool = True) -> list["MaterializedDataset"]:
        """Split into n shards (reference: dataset.py split; Train's
        DataConfig splits streams per worker)."""
        mat = self.repartition(n).materialize()
        refs = mat._refs
        shards = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [MaterializedDataset(s) for s in shards]

    def streaming_split(self, n: int) -> list["DataIterator"]:
        return [DataIterator(s) for s in self.split(n)]

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, blk in enumerate(self.iter_blocks()):
            if not B.num_rows(blk):
                continue
            # Arrow blocks pass through; numpy dicts convert (ndim>=2
            # columns become tensor extension columns).
            tbl = B.to_arrow(blk)
            pq.write_table(tbl, os.path.join(path, f"part-{i:05d}.parquet"))

    def stats(self) -> str:
        return self._plan.describe()

    def __repr__(self):
        return f"Dataset(plan={self._plan.describe()!r})"


class MaterializedDataset(Dataset):
    """A dataset whose blocks are pinned refs (reference: MaterializedDataset)."""

    def __init__(self, refs: list):
        self._refs = refs
        super().__init__(P.LogicalPlan([P.RefSource(refs)]))


class GroupedData:
    """Result of ds.groupby(key) (reference: grouped_data.py)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, kind: str, on: str | None) -> Dataset:
        out = kind if on is None else f"{kind}({on})"
        return self._ds._with(P.GroupByAggregate(self._key, [(kind, on, out)]))

    def count(self) -> Dataset:
        return self._agg("count", None)

    def sum(self, on: str) -> Dataset:
        return self._agg("sum", on)

    def min(self, on: str) -> Dataset:
        return self._agg("min", on)

    def max(self, on: str) -> Dataset:
        return self._agg("max", on)

    def mean(self, on: str) -> Dataset:
        return self._agg("mean", on)

    def aggregate(self, *specs) -> Dataset:
        """specs: (kind, column) tuples."""
        aggs = [(k, c, f"{k}({c})") for k, c in specs]
        return self._ds._with(P.GroupByAggregate(self._key, aggs))

    def map_groups(self, fn, *, batch_format="numpy") -> Dataset:
        return self._ds._with(P.MapGroups(self._key, fn, batch_format))


class DataIterator:
    """Per-worker shard iterator (reference: DataIterator / iter_torch_batches)."""

    def __init__(self, shard: MaterializedDataset):
        self._shard = shard

    def iter_batches(self, **kw) -> Iterator:
        return self._shard.iter_batches(**kw)

    def iter_rows(self) -> Iterator[dict]:
        return self._shard.iter_rows()

    def count(self) -> int:
        return self._shard.count()
