"""Datasources: read tasks that produce blocks.

Mirrors the reference's datasource/read-task split (reference:
python/ray/data/datasource/datasource.py `Datasource.get_read_tasks`,
python/ray/data/read_api.py): a datasource plans a list of independent
`ReadTask`s, each a zero-arg callable producing one block, so reads
parallelize as ordinary tasks.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Iterable

import numpy as np

from ray_tpu.data import block as B

# A ReadTask is a picklable zero-arg callable returning a Block.
ReadTask = Callable[[], B.Block]


class _RangeRead:
    def __init__(self, start: int, end: int):
        self.start, self.end = start, end

    def __call__(self) -> B.Block:
        return {"id": np.arange(self.start, self.end, dtype=np.int64)}


class _ItemsRead:
    def __init__(self, items: list):
        self.items = items

    def __call__(self) -> B.Block:
        return B.from_items(self.items)


class _ParquetRead:
    def __init__(self, path: str, columns=None):
        self.path, self.columns = path, columns

    def __call__(self) -> B.Block:
        import pyarrow.parquet as pq

        return B.from_arrow(pq.read_table(self.path, columns=self.columns))


class _CSVRead:
    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> B.Block:
        import pyarrow.csv as pacsv

        return B.from_arrow(pacsv.read_csv(self.path))


class _JSONRead:
    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> B.Block:
        import json

        rows = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return B.from_rows(rows)


class _TextRead:
    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> B.Block:
        with open(self.path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return {"text": B._as_array(lines)}


class _NumpyRead:
    def __init__(self, path: str):
        self.path = path

    def __call__(self) -> B.Block:
        return {"data": np.load(self.path)}


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(os.path.join(p, f) for f in os.listdir(p) if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def range_tasks(n: int, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, n) if n else 1)
    step = (n + parallelism - 1) // parallelism if n else 0
    tasks: list[ReadTask] = []
    for s in range(0, n, step or 1):
        tasks.append(_RangeRead(s, min(s + step, n)))
    return tasks or [_RangeRead(0, 0)]


def items_tasks(items: list, parallelism: int) -> list[ReadTask]:
    n = len(items)
    parallelism = max(1, min(parallelism, n) if n else 1)
    step = (n + parallelism - 1) // parallelism if n else 0
    tasks: list[ReadTask] = []
    for s in range(0, n, step or 1):
        tasks.append(_ItemsRead(items[s : s + step]))
    return tasks or [_ItemsRead([])]


def file_tasks(paths, kind: str, **kw) -> list[ReadTask]:
    cls = {
        "parquet": _ParquetRead,
        "csv": _CSVRead,
        "json": _JSONRead,
        "text": _TextRead,
        "numpy": _NumpyRead,
    }[kind]
    files = _expand_paths(paths)
    if not files:
        raise FileNotFoundError(f"no files match {paths!r}")
    return [cls(f, **kw) if kind == "parquet" else cls(f) for f in files]
