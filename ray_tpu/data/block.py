"""Blocks: the unit of data held in the object store.

The reference's block is a pyarrow Table in plasma (reference:
python/ray/data/block.py, `BlockAccessor`). Here the canonical block is a
**columnar dict of numpy arrays** — the zero-copy host format for feeding
JAX/TPU input pipelines — with pandas/arrow conversion at the edges.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

# A block is dict[str, np.ndarray]; all columns share length.
Block = dict


def _as_array(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    arr = np.asarray(values)
    if arr.dtype == object:
        # Ragged / mixed values stay as object arrays (mirrors ArrowVariableShapedTensor).
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
    return arr


def from_rows(rows: Iterable[dict]) -> Block:
    rows = list(rows)
    if not rows:
        return {}
    cols: dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        if r.keys() != cols.keys():
            for k in r:
                cols.setdefault(k, [None] * (len(next(iter(cols.values()), [])) ))
        for k in cols:
            cols[k].append(r.get(k))
    return {k: _as_array(v) for k, v in cols.items()}


def from_items(items: Iterable[Any]) -> Block:
    items = list(items)
    if items and isinstance(items[0], dict):
        return from_rows(items)
    return {"item": _as_array(items)}


def from_pandas(df) -> Block:
    return {c: df[c].to_numpy() for c in df.columns}


def from_arrow(table) -> Block:
    return {name: col.to_numpy(zero_copy_only=False) for name, col in zip(table.column_names, table.columns)}


def num_rows(block: Block) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def size_bytes(block: Block) -> int:
    total = 0
    for arr in block.values():
        if arr.dtype == object:
            total += sum(getattr(v, "nbytes", 64) for v in arr)
        else:
            total += arr.nbytes
    return total


def schema(block: Block) -> dict[str, Any]:
    return {k: v.dtype for k, v in block.items()}


def slice_block(block: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in block.items()}


def take_idx(block: Block, idx: np.ndarray) -> Block:
    return {k: v[idx] for k, v in block.items()}


def concat(blocks: list[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return {}
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def to_rows(block: Block) -> Iterator[dict]:
    n = num_rows(block)
    keys = list(block.keys())
    for i in range(n):
        yield {k: block[k][i] for k in keys}


def to_pandas(block: Block):
    import pandas as pd

    return pd.DataFrame({k: list(v) if v.dtype == object else v for k, v in block.items()})


def to_batch(block: Block, batch_format: str):
    """Convert a block to the user-facing batch format."""
    if batch_format in ("numpy", "default", None):
        return dict(block)
    if batch_format == "pandas":
        return to_pandas(block)
    if batch_format == "pyarrow":
        import pyarrow as pa

        return pa.table({k: list(v) if v.dtype == object else v for k, v in block.items()})
    raise ValueError(f"unknown batch_format {batch_format!r}")


def from_batch(batch) -> Block:
    """Normalize a user-returned batch back into a block."""
    if batch is None:
        return {}
    if isinstance(batch, dict):
        return {k: _as_array(v) for k, v in batch.items()}
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return from_pandas(batch)
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return from_arrow(batch)
    except ImportError:
        pass
    raise TypeError(f"map_batches must return dict/DataFrame/Table, got {type(batch)}")
