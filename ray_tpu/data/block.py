"""Blocks: the unit of data held in the object store.

The reference's block is a pyarrow Table in plasma (reference:
python/ray/data/block.py, `BlockAccessor`; arrow_block.py:213
ArrowBlockAccessor). Here a block is EITHER a **columnar dict of numpy
arrays** — the zero-copy host format for feeding JAX/TPU input
pipelines — or a **pyarrow Table** (Arrow-native scans keep their
table; see arrow_block.py). Every function below dispatches on the
block kind; ops that need column math call :func:`ensure_numpy` once
at their kernel entry.
"""

from __future__ import annotations

from typing import Any, Iterable, Iterator

import numpy as np

try:
    # Eager import so the tensor extension type registers in EVERY
    # process that touches blocks BEFORE any table is deserialized or
    # scanned — a fresh worker reading parquet written elsewhere must
    # already know ray_tpu.tensor or the column degrades to a plain
    # fixed_size_list and loses its shape.
    from ray_tpu.data import arrow_block as _arrow_mod
except ImportError:  # pyarrow not installed: numpy-dict blocks only
    _arrow_mod = None

# A block is dict[str, np.ndarray] | pyarrow.Table; columns share length.
Block = Any


def _arrow():
    if _arrow_mod is None:
        raise ImportError("pyarrow is required for Arrow blocks")
    return _arrow_mod


def _is_table(block) -> bool:
    if isinstance(block, dict) or block is None or _arrow_mod is None:
        return False
    return _arrow_mod.is_arrow_block(block)


def ensure_numpy(block: Block) -> dict:
    """Normalize to the numpy column dict (one conversion, at the edge
    where column math happens — sort/groupby/join kernels)."""
    if _is_table(block):
        return _arrow().numpy_dict_from_table(block)
    return block


def _as_array(values) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    arr = np.asarray(values)
    if arr.dtype == object:
        # Ragged / mixed values stay as object arrays (mirrors ArrowVariableShapedTensor).
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
    return arr


def from_rows(rows: Iterable[dict]) -> Block:
    rows = list(rows)
    if not rows:
        return {}
    cols: dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        if r.keys() != cols.keys():
            for k in r:
                cols.setdefault(k, [None] * (len(next(iter(cols.values()), [])) ))
        for k in cols:
            cols[k].append(r.get(k))
    return {k: _as_array(v) for k, v in cols.items()}


def from_items(items: Iterable[Any]) -> Block:
    items = list(items)
    if items and isinstance(items[0], dict):
        return from_rows(items)
    return {"item": _as_array(items)}


def from_pandas(df) -> Block:
    return {c: df[c].to_numpy() for c in df.columns}


def from_arrow(table) -> Block:
    """Arrow tables ARE blocks now — the scan's table flows through
    the pipeline without an eager numpy copy (conversion happens only
    at a numpy/pandas batch edge or a column-math kernel)."""
    return table


def num_rows(block: Block) -> int:
    if _is_table(block):
        return _arrow().num_rows(block)
    if not block:
        return 0
    return len(next(iter(block.values())))


def size_bytes(block: Block) -> int:
    if _is_table(block):
        return _arrow().size_bytes(block)
    total = 0
    for arr in block.values():
        if arr.dtype == object:
            total += sum(getattr(v, "nbytes", 64) for v in arr)
        else:
            total += arr.nbytes
    return total


def schema(block: Block) -> dict[str, Any]:
    if _is_table(block):
        return _arrow().schema(block)
    return {k: v.dtype for k, v in block.items()}


def slice_block(block: Block, start: int, end: int) -> Block:
    if _is_table(block):
        return _arrow().slice_table(block, start, end)  # zero-copy
    return {k: v[start:end] for k, v in block.items()}


def take_idx(block: Block, idx: np.ndarray) -> Block:
    if _is_table(block):
        return _arrow().take_table(block, idx)
    return {k: v[idx] for k, v in block.items()}


def concat(blocks: list[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return {}
    if all(_is_table(b) for b in blocks):
        return _arrow().concat_tables(blocks)
    # Mixed ancestry (an Arrow scan unioned with numpy-born blocks):
    # land on the numpy dict, the canonical compute format.
    blocks = [ensure_numpy(b) for b in blocks]
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def to_rows(block: Block) -> Iterator[dict]:
    if _is_table(block):
        yield from _arrow().to_rows(block)
        return
    n = num_rows(block)
    keys = list(block.keys())
    for i in range(n):
        yield {k: block[k][i] for k in keys}


def to_pandas(block: Block):
    import pandas as pd

    if _is_table(block):
        return block.to_pandas()
    return pd.DataFrame({k: list(v) if v.dtype == object else v for k, v in block.items()})


def to_arrow(block: Block):
    """Block → pyarrow Table; ndim>=2 numpy columns become tensor
    extension columns (arrow_block.ArrowTensorType)."""
    if _is_table(block):
        return block
    return _arrow().table_from_numpy_dict(block)


def to_batch(block: Block, batch_format: str):
    """Convert a block to the user-facing batch format."""
    if batch_format in ("numpy", "default", None):
        return ensure_numpy(block) if _is_table(block) else dict(block)
    if batch_format == "pandas":
        return to_pandas(block)
    if batch_format == "pyarrow":
        return to_arrow(block)
    raise ValueError(f"unknown batch_format {batch_format!r}")


def from_batch(batch) -> Block:
    """Normalize a user-returned batch back into a block."""
    if batch is None:
        return {}
    if isinstance(batch, dict):
        return {k: _as_array(v) for k, v in batch.items()}
    try:
        import pandas as pd

        if isinstance(batch, pd.DataFrame):
            return from_pandas(batch)
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(batch, pa.Table):
            return batch  # Arrow-native: stays a table
    except ImportError:
        pass
    raise TypeError(f"map_batches must return dict/DataFrame/Table, got {type(batch)}")
