"""Compiled-program profiler: HLO roofline + in-program step attribution.

Everything the goodput plane measures today (comm_exposed_ratio,
host_sync_exposed_ratio, phase spans) stops at the jit boundary — the
compiled step itself is a black box. This module opens it, in two
halves that join into one MFU decomposition:

**Static (analytic)** — :func:`analyze_compiled` lowers a train step
once and walks its optimized HLO (``_private/xla_profile.py``), bucketing
every instruction into matmul / collective / elementwise_fusion /
layout and pricing each bucket against a per-chip roofline: PEAK_FLOPS
(telemetry's table) for math, the HBM_GBPS table for bytes, the
ICI_GBPS table (with standard algorithm wire factors) for collectives.
The result is an *analytic ideal step time* and per-category floors.
Honesty caveat: these are cost-model numbers, not measurements —
``cost_analysis()``/HLO byte counts assume perfect fusion-boundary
traffic and peak sustained bandwidth.

**Empirical (measured)** — a capture request (head ``profile_capture``
fan-out, or :func:`request_capture` locally) arms the per-step hook that
``telemetry.finish_step`` calls. At the next step boundary the hook
wraps PROFILE_CAPTURE_STEPS steps in the hardened ``jax_profile``
tracer, parses the ``*.xplane.pb`` into per-category measured seconds,
and emits a ``profile:step`` span the head folds into the goodput
ledger (decomposition gauges + the regression-sentinel fingerprint).

**The join** — :func:`attribution_report` decomposes the measured step
wall into compute_floor / comm_in_program / hbm_bound / host_gap /
unattributed shares and names the dominant non-compute consumer: the
answer to "where does the missing MFU go".

Failure contract: nothing here may break a training step. The hook is a
two-branch no-op while disarmed (pinned <50µs by the perf-floor test),
and every capture-path failure degrades to one warning log.
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import threading
import time

logger = logging.getLogger(__name__)

CATEGORIES = (
    "compute_floor", "comm_in_program", "hbm_bound", "host_gap",
    "unattributed",
)

# Peak HBM bandwidth per chip, GB/s, by TPU generation (public spec
# sheets; the bandwidth analogue of telemetry.PEAK_FLOPS and
# runtime/memory.DEVICE_HBM_GB).
HBM_GBPS = {
    "v5e": 819.0,
    "v5litepod": 819.0,
    "v5p": 2765.0,
    "v4": 1228.0,
    "v6e": 1638.0,
}
DEFAULT_HBM_GBPS = 819.0

# Per-chip ICI bandwidth, GB/s (one-directional aggregate across links).
ICI_GBPS = {
    "v5e": 200.0,
    "v5litepod": 200.0,
    "v5p": 600.0,
    "v4": 300.0,
    "v6e": 448.0,
}
DEFAULT_ICI_GBPS = 200.0


def _chip_table_lookup(table: dict[str, float], default: float) -> float:
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    # tpulint: allow(broad-except reason=device probing for a roofline denominator; any jax/backend failure falls back to the documented default rather than failing analysis)
    except Exception:  # noqa: BLE001 - no jax/devices: documented default
        return default
    for name, value in table.items():
        if name in kind:
            return value
    return default


def hbm_bandwidth_per_chip() -> float:
    """Peak HBM bytes/s of this host's chip generation."""
    return _chip_table_lookup(HBM_GBPS, DEFAULT_HBM_GBPS) * 1e9


def ici_bandwidth_per_chip() -> float:
    """Peak ICI bytes/s of this host's chip generation."""
    return _chip_table_lookup(ICI_GBPS, DEFAULT_ICI_GBPS) * 1e9


def collective_wire_factor(op: str, group: int | None) -> float:
    """Wire-traffic multiple of the buffer size for one collective on a
    ring of ``group`` members: allreduce moves 2(n-1)/n of the buffer
    per chip, allgather/reduce-scatter (n-1)/n, permute 1."""
    n = group or 1
    if n <= 1:
        return 0.0
    base = op.replace("-start", "")
    if "all-reduce" in base or "allreduce" in base:
        return 2.0 * (n - 1) / n
    if ("all-gather" in base or "reduce-scatter" in base
            or "allgather" in base or "reducescatter" in base):
        return (n - 1) / n
    return 1.0


def price_categories(
    walk: dict,
    peak_flops: float | None = None,
    hbm_bps: float | None = None,
    ici_bps: float | None = None,
) -> dict:
    """Roofline-price the HLO walker's category table into per-category
    floor seconds. matmul takes max(flops-bound, bytes-bound); layout
    and elementwise are HBM-bound; collectives are ICI wire time."""
    from ray_tpu.train import telemetry

    peak = peak_flops or telemetry.peak_flops_per_chip()
    hbm = hbm_bps or hbm_bandwidth_per_chip()
    ici = ici_bps or ici_bandwidth_per_chip()
    cats = walk["categories"]
    floors = {}
    floors["matmul"] = max(
        cats["matmul"]["flops"] / peak, cats["matmul"]["bytes"] / hbm
    )
    floors["elementwise_fusion"] = cats["elementwise_fusion"]["bytes"] / hbm
    floors["layout"] = cats["layout"]["bytes"] / hbm
    wire = 0.0
    for op in walk["collective_ops"]:
        wire += op["bytes"] * collective_wire_factor(op["op"], op["group"])
    floors["collective"] = wire / ici
    return floors


def analyze_compiled(compiled) -> dict:
    """Static profile of one compiled executable: HLO category walk +
    roofline floors + the fingerprint signature the regression sentinel
    keys on. ``compiled`` is the result of ``jit(f).lower(...).
    compile()``."""
    text = compiled.as_text()
    walk = _analyze_text(text)
    # Cross-check against XLA's own aggregate (analytic too, but
    # independently derived). Counts each while body ONCE, so the
    # walker's trip-multiplied flops should be >= the aggregate.
    agg = {}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        agg = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes": float(cost.get("bytes accessed", 0.0)),
        }
    # tpulint: allow(broad-except reason=cost_analysis is a cross-check only; backends without it still get the HLO-walk profile)
    except Exception:  # noqa: BLE001
        pass
    return _finish_static(walk, agg)


def _analyze_text(text: str) -> dict:
    from ray_tpu._private import xla_profile

    return xla_profile.analyze_hlo_text(text)


def _finish_static(walk: dict, agg: dict) -> dict:
    floors = price_categories(walk)
    cats = walk["categories"]
    total_flops = sum(c["flops"] for c in cats.values())
    total_bytes = sum(c["bytes"] for c in cats.values())
    # Signature: the category shape of the program, stable across
    # processes (HLO text itself embeds unstable ids). Rounded so
    # float-noise in pricing can't fork fingerprints.
    sig_src = json.dumps(
        {
            k: [round(v["flops"]), round(v["bytes"]), v["ops"]]
            for k, v in sorted(cats.items())
        },
        sort_keys=True,
    )
    return {
        "sig": hashlib.sha1(sig_src.encode()).hexdigest()[:16],
        "categories": {
            k: {**v, "floor_s": floors[k]} for k, v in cats.items()
        },
        "ideal_step_s": sum(floors.values()),
        "flops_total": total_flops,
        "bytes_total": total_bytes,
        "cost_analysis": agg,
        "collective_ops": len(walk["collective_ops"]),
        "while_trips": walk["while_trips"],
    }


def analyze_train_step(
    cfg=None, batch_size: int = 8, seq: int | None = None
) -> dict:
    """Lower the flagship ``jit_train_step`` once (no execution) and
    statically profile it. Defaults to the bench preset at its bench
    shapes; pass ``cfg`` (e.g. PRESETS['tiny']) for fast tests."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import PRESETS
    from ray_tpu.parallel import make_mesh
    from ray_tpu.train.step import (
        init_train_state,
        jit_train_step,
        make_optimizer,
    )

    if cfg is None:
        cfg = PRESETS["bench"]
    if seq is None:
        seq = min(2048, cfg.max_seq_len)
    opt = make_optimizer(total_steps=1000)
    mesh = make_mesh({"dp": len(jax.devices())})
    step = jit_train_step(cfg, opt, mesh)
    state = init_train_state(jax.random.key(0), cfg, opt)
    tokens = jnp.zeros((batch_size, seq + 1), dtype=jnp.int32)
    compiled = step.lower(state, {"tokens": tokens}).compile()
    static = analyze_compiled(compiled)
    static["batch_size"] = batch_size
    static["seq"] = seq
    static["model_flops_per_step"] = cfg.flops_per_token(seq) * (
        batch_size * seq
    )
    return static


# ------------------------------------------------------- attribution
def attribution_report(
    measured: dict,
    wall_s: float,
    steps: int,
    static: dict | None = None,
    model_flops_per_step: float | None = None,
) -> dict:
    """Join one capture's measured per-category seconds with the static
    roofline into the MFU decomposition.

    ``measured`` is ``xla_profile.measured_category_seconds`` output for
    ``steps`` steps totalling ``wall_s`` host seconds. Per-step
    decomposition (seconds, then shares of the step wall):

    - compute_floor: matmul time — the analytic floor when a static
      profile is supplied (what a perfect program would still pay),
      else the measured matmul seconds;
    - comm_in_program: measured collective time inside the program;
    - hbm_bound: measured elementwise/fusion + layout time (bandwidth,
      not math);
    - host_gap: step wall the device spent idle (wall − device busy);
    - unattributed: the remainder (tracer gaps, measured matmul above
      the floor, uncategorized ops).

    Multi-threaded CPU backends can sum concurrent leaf ops past the
    wall; measured seconds are normalized by min(1, wall/busy) so
    shares stay meaningful on every backend.
    """
    steps = max(1, steps)
    wall_step = wall_s / steps
    cats = {k: v / steps for k, v in measured["categories"].items()}
    busy_step = measured["device_busy_s"] / steps
    scale = 1.0
    if busy_step > 0 and wall_step > 0:
        scale = min(1.0, wall_step / busy_step)
    matmul_s = cats["matmul"] * scale
    comm_s = cats["collective"] * scale
    hbm_s = (cats["elementwise_fusion"] + cats["layout"]) * scale
    host_gap_s = max(0.0, wall_step - busy_step * scale)
    compute_s = matmul_s
    if static is not None:
        floor = static["categories"]["matmul"]["floor_s"]
        if 0.0 < floor <= matmul_s:
            compute_s = floor
    used = compute_s + comm_s + hbm_s + host_gap_s
    unattributed_s = max(0.0, wall_step - used)
    seconds = {
        "compute_floor": compute_s,
        "comm_in_program": comm_s,
        "hbm_bound": hbm_s,
        "host_gap": host_gap_s,
        "unattributed": unattributed_s,
    }
    shares = {
        k: (v / wall_step if wall_step > 0 else 0.0)
        for k, v in seconds.items()
    }
    gaps = {k: v for k, v in seconds.items() if k != "compute_floor"}
    dominant = max(gaps, key=gaps.get) if wall_step > 0 else "unattributed"
    report = {
        "step_s": wall_step,
        "steps": steps,
        "device_busy_s": busy_step * scale,
        "seconds": {k: round(v, 6) for k, v in seconds.items()},
        "shares": {k: round(v, 6) for k, v in shares.items()},
        "dominant_gap": dominant,
        "sig": (static or {}).get("sig", ""),
    }
    flops = model_flops_per_step or (static or {}).get(
        "model_flops_per_step"
    )
    if flops and wall_step > 0:
        from ray_tpu.train import telemetry

        try:
            import jax

            n_chips = max(1, len(jax.devices()))
        # tpulint: allow(broad-except reason=chip counting for an MFU denominator only; degrade to single-chip math)
        except Exception:  # noqa: BLE001
            n_chips = 1
        peak = telemetry.peak_flops_per_chip() * n_chips
        report["mfu"] = round(flops / (wall_step * peak), 6)
    return report


def _read_capture(path: str) -> dict | None:
    """Sum measured category seconds across every xplane.pb under one
    capture run directory; None when the tracer wrote nothing."""
    from ray_tpu._private import xla_profile

    files = sorted(glob.glob(f"{path}/**/*.xplane.pb", recursive=True))
    if not files:
        return None
    total = None
    for f in files:
        with open(f, "rb") as fh:
            one = xla_profile.measured_category_seconds(fh.read())
        if total is None:
            total = one
        else:
            for k, v in one["categories"].items():
                total["categories"][k] += v
            total["device_busy_s"] += one["device_busy_s"]
            total["events"] += one["events"]
    return total


# -------------------------------------------------- capture machinery
# Module state machine, driven by the per-step hook telemetry calls.
# _armed is the single fast-path gate: False == hook returns in two
# branches (the pinned disabled path).
_armed = False
_lock = threading.Lock()
_pending_steps = 0
_active: dict | None = None
_statics: dict[str, dict] = {}  # job → static profile (register_static)
_last_reports: dict[str, dict] = {}  # job → last attribution report


def profiling_enabled() -> bool:
    from ray_tpu._private import config

    return config.get("PROFILE")


def register_static(job: str, static: dict) -> None:
    """Attach a static profile to a job so captures join against its
    analytic floors and fingerprint signature."""
    _statics[job] = static


def request_capture(steps: int | None = None) -> None:
    """Arm the step hook: the next step boundary starts an on-device
    trace of ``steps`` (default PROFILE_CAPTURE_STEPS) steps."""
    global _armed, _pending_steps
    if not profiling_enabled():
        logger.warning(
            "profile capture requested but RAY_TPU_PROFILE=0; ignoring"
        )
        return
    if steps is None:
        from ray_tpu._private import config

        steps = config.get("PROFILE_CAPTURE_STEPS")
    with _lock:
        _pending_steps = max(1, int(steps))
        _armed = True


def note_capture_request(msg: dict) -> None:
    """Pubsub fan-out entry point (head ``profile_capture`` event on the
    collective channel)."""
    request_capture(msg.get("steps"))


def last_report(job: str | None = None) -> dict | None:
    if job is not None:
        return _last_reports.get(job)
    for rep in _last_reports.values():
        return rep
    return None


def step_hook(ctx, step_s: float) -> None:
    """Per-step profiler hook, called by telemetry.finish_step on the
    step success path. MUST never raise and must cost nothing while
    disarmed (the perf-floor test pins this branch)."""
    global _armed, _active, _pending_steps
    if not _armed:
        return
    try:
        _step_hook_armed(ctx, step_s)
    # tpulint: allow(broad-except reason=capture failures must degrade to a warning, never an exception in the step loop — the acceptance contract of this plane)
    except Exception:  # noqa: BLE001
        logger.warning(
            "profile capture failed; disarming", exc_info=True
        )
        with _lock:
            _active = None
            _pending_steps = 0
            _armed = False


def _step_hook_armed(ctx, step_s: float) -> None:
    global _armed, _active, _pending_steps
    with _lock:
        if _active is None:
            if _pending_steps <= 0:
                _armed = False
                return
            steps = _pending_steps
            _pending_steps = 0
            from ray_tpu.util import tracing

            cm = tracing.jax_profile()
            cap = cm.__enter__()
            _active = {
                "cm": cm,
                "cap": cap,
                "left": steps,
                "steps": steps,
                "wall": 0.0,
                "t0": time.time(),
            }
            return
        act = _active
        act["left"] -= 1
        act["wall"] += step_s
        if act["left"] > 0:
            return
        _active = None
        if _pending_steps <= 0:
            _armed = False
    act["cm"].__exit__(None, None, None)
    _finish_capture(ctx, act)


def _finish_capture(ctx, act: dict) -> None:
    path = act["cap"].path
    measured = _read_capture(path) if path else None
    if measured is None:
        logger.warning(
            "profile capture wrote no parseable trace under %r", path
        )
        return
    job = ctx.experiment_name
    static = _statics.get(job)
    report = attribution_report(
        measured, act["wall"], act["steps"], static=static
    )
    if not report["sig"]:
        report["sig"] = job  # fingerprint key without a static profile
    report["path"] = path
    _last_reports[job] = report
    from ray_tpu.util import tracing

    tracing.emit_span(
        "profile:step",
        act["t0"],
        act["wall"],
        train_job=job,
        train_rank=ctx.rank,
        train_attempt=ctx.attempt,
        profile_sig=report["sig"],
        profile_steps=act["steps"],
        profile_step_s=round(report["step_s"], 6),
        profile_shares=report["shares"],
        profile_dominant=report["dominant_gap"],
        path=path or "",
    )
    logger.info(
        "profile capture %s: step %.4fs dominant_gap=%s shares=%s",
        job, report["step_s"], report["dominant_gap"], report["shares"],
    )


def _reset_for_tests() -> None:
    global _armed, _pending_steps, _active
    with _lock:
        _armed = False
        _pending_steps = 0
        _active = None
    _statics.clear()
    _last_reports.clear()


def profile_train_step(
    cfg=None, batch_size: int = 8, seq: int | None = None,
    steps: int | None = None,
) -> dict:
    """One-process convenience used by bench.py and the CPU acceptance
    test: statically profile the flagship step, run ``steps`` of it
    under the tracer, and return the joined attribution report (with
    the static profile under ``"static"``)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import config
    from ray_tpu.models import PRESETS
    from ray_tpu.parallel import make_mesh
    from ray_tpu.train.step import (
        init_train_state,
        jit_train_step,
        make_optimizer,
    )
    from ray_tpu.util import tracing

    if cfg is None:
        cfg = PRESETS["bench"]
    if seq is None:
        seq = min(2048, cfg.max_seq_len)
    if steps is None:
        steps = config.get("PROFILE_CAPTURE_STEPS")
    opt = make_optimizer(total_steps=1000)
    mesh = make_mesh({"dp": len(jax.devices())})
    step = jit_train_step(cfg, opt, mesh)
    state = init_train_state(jax.random.key(0), cfg, opt)
    tokens = jax.random.randint(
        jax.random.key(1), (batch_size, seq + 1), 0, cfg.vocab_size
    )
    batch = {"tokens": tokens}
    compiled = step.lower(state, batch).compile()
    static = analyze_compiled(compiled)
    static["model_flops_per_step"] = cfg.flops_per_token(seq) * (
        batch_size * seq
    )
    # Warmup outside the trace (compile is done; first steps still run
    # cold caches), then capture.
    for _ in range(2):
        state, metrics = step(state, batch)
    jnp.asarray(metrics["loss"]).block_until_ready()
    with tracing.jax_profile() as cap:
        # Timer starts inside: the profiler's one-time start_trace
        # init (seconds on first use) must not read as host_gap.
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        jnp.asarray(metrics["loss"]).block_until_ready()
        wall = time.perf_counter() - t0
    measured = _read_capture(cap.path) if cap.path else None
    if measured is None:
        raise RuntimeError(
            f"profiler wrote no parseable trace under {cap.path!r}"
        )
    report = attribution_report(measured, wall, steps, static=static)
    report["path"] = cap.path
    report["static"] = static
    return report
