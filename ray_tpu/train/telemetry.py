"""Train-step telemetry: phase spans, per-step MFU, goodput events.

Always-cheap instrumentation for the train loop (reference intent:
ray.train's TrainingReport/metrics plumbing plus the per-step profiling
the BENCH/PROFILE rounds hand-rolled). A step is wrapped by
``ray_tpu.train.step_span()`` (or closed implicitly by ``report()``); on
completion it

- observes per-phase durations into ``ray_tpu_train_step_phase_seconds``
  (data-wait / compute / collective / checkpoint / whole step),
- computes per-step MFU from the step's FLOP count against the chip
  generation's peak (the same table bench.py normalizes with) and sets
  ``ray_tpu_train_mfu``,
- emits ``train:step`` / ``train:<phase>`` SPAN events onto the
  task-event pipeline. Rank 0's step spans are what the head folds into
  per-job **goodput** (productive step time vs. time lost to stalls and
  attempt restarts — see HeadService._train_step_event); all ranks'
  spans render as slices in ``ray_tpu timeline``.

Disable with RAY_TPU_TRAIN_TELEMETRY=0: ``step()`` then hands back a
shared no-op timer whose overhead a perf-floor test pins
(tests/test_perf_floors.py), so telemetry can never quietly tax the
train loop.
"""

from __future__ import annotations

import contextlib
import time

from ray_tpu.util.metrics import Counter, Gauge, Histogram

# Peak bf16 FLOP/s per chip by TPU generation (public spec sheets; the
# same table bench.py uses for its vs_baseline normalization).
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}
DEFAULT_PEAK_FLOPS = 197e12

STEP_PHASE_SECONDS = Histogram(
    "ray_tpu_train_step_phase_seconds",
    "train step time by phase ('step' = the whole step)",
    boundaries=(
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0,
    ),
    tag_keys=("job", "phase"),
)
MFU_GAUGE = Gauge(
    "ray_tpu_train_mfu",
    "model FLOPs utilization of this worker's most recent step",
    tag_keys=("job",),
)
STEPS_TOTAL = Counter(
    "ray_tpu_train_steps_total",
    "completed train steps",
    tag_keys=("job",),
)


def telemetry_enabled() -> bool:
    from ray_tpu._private import config

    return config.get("TRAIN_TELEMETRY")


def peak_flops_per_chip() -> float:
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    except Exception:  # noqa: BLE001 - no jax/devices: proxy peak
        return DEFAULT_PEAK_FLOPS
    for name, flops in PEAK_FLOPS.items():
        if name in kind:
            return flops
    return DEFAULT_PEAK_FLOPS


class _NoopPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NoopStepTimer:
    """Disabled path: attribute-compatible with StepTimer, shared and
    allocation-free."""

    __slots__ = ()
    phases: dict = {}
    _noop = _NoopPhase()

    def phase(self, name: str):
        return self._noop


NOOP_STEP = NoopStepTimer()


class StepTimer:
    """Measures one train step and its phases. Phase timing is a
    perf_counter pair and a dict store; span/metric emission happens
    once, at step end (finish_step)."""

    __slots__ = ("phases", "flops", "start", "_t0", "_events")

    def __init__(self, flops: float | None = None):
        self.phases: dict[str, float] = {}
        self.flops = flops
        self.start = time.time()
        self._t0 = time.perf_counter()
        # (name, wall_start, dur) per phase invocation, for timeline
        # slices placed at their true offsets.
        self._events: list[tuple[str, float, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str):
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            d = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + d
            self._events.append((name, wall, d))

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0


def compute_mfu(flops: float | None, dur: float) -> float | None:
    if not flops or dur <= 0:
        return None
    try:
        import jax

        n_chips = max(1, len(jax.devices()))
    except Exception:  # noqa: BLE001
        n_chips = 1
    return flops / (dur * peak_flops_per_chip() * n_chips)


def finish_step(ctx, timer: StepTimer) -> None:
    """Close a completed step: metrics + SPAN emission + context
    bookkeeping. Called only on the step's success path — a step that
    raised must not count as productive time (its tail shows up as
    restart loss in the head's goodput accounting instead)."""
    dur = timer.elapsed()
    job = ctx.experiment_name
    STEPS_TOTAL.inc(tags={"job": job})
    STEP_PHASE_SECONDS.observe(dur, tags={"job": job, "phase": "step"})
    for ph, s in timer.phases.items():
        STEP_PHASE_SECONDS.observe(s, tags={"job": job, "phase": ph})
    mfu = compute_mfu(timer.flops, dur)
    if mfu is not None:
        MFU_GAUGE.set(mfu, tags={"job": job})
    _emit_step_span(
        ctx, timer.start, dur, phases=dict(timer.phases), mfu=mfu,
        degraded_frac=_take_degraded_frac(ctx),
    )
    from ray_tpu.util import tracing

    for name, wall, d in timer._events:
        tracing.emit_span(
            f"train:{name}", wall, d,
            train_job=job, train_attempt=ctx.attempt, train_rank=ctx.rank,
        )
    ctx._step_index += 1
    ctx._used_step_timer = True
    ctx._last_report_wall = time.time()


def implicit_step(ctx, now: float, metrics: dict) -> None:
    """report()-closed step for loops that never use step_span():
    the stretch since the previous report (or loop start) is one step.
    Keeps goodput accounting working for every existing train loop."""
    base = ctx._last_report_wall or ctx._loop_start_wall
    if base is None:
        return
    dur = max(0.0, now - base)
    job = ctx.experiment_name
    STEPS_TOTAL.inc(tags={"job": job})
    STEP_PHASE_SECONDS.observe(dur, tags={"job": job, "phase": "step"})
    mfu = metrics.get("mfu") if isinstance(metrics, dict) else None
    mfu = float(mfu) if isinstance(mfu, (int, float)) else None
    if mfu is not None:
        MFU_GAUGE.set(mfu, tags={"job": job})
    phases = {}
    ckpt_s = getattr(ctx, "_last_checkpoint_s", 0.0)
    if ckpt_s:
        phases["checkpoint"] = ckpt_s
        STEP_PHASE_SECONDS.observe(
            ckpt_s, tags={"job": job, "phase": "checkpoint"}
        )
    _emit_step_span(
        ctx, base, dur, phases=phases, mfu=mfu,
        degraded_frac=_take_degraded_frac(ctx),
    )
    ctx._step_index += 1


def _take_degraded_frac(ctx) -> float:
    """Drain this step's partial-collective skip fractions into one
    degraded fraction (the worst op bounds the step: a gradient sync
    that excluded 1/4 of the ranks degrades the whole step's update by
    that fraction, however many clean ops surrounded it)."""
    fracs = getattr(ctx, "_partial_fracs", None)
    if not fracs:
        return 0.0
    frac = min(1.0, max(fracs))
    fracs.clear()
    return frac


def _emit_step_span(ctx, start, dur, phases, mfu, degraded_frac=0.0) -> None:
    from ray_tpu.util import tracing

    attrs = dict(
        train_job=ctx.experiment_name,
        train_attempt=ctx.attempt,
        train_rank=ctx.rank,
        train_step=ctx._step_index,
        phases=phases,
    )
    if mfu is not None:
        attrs["mfu"] = round(mfu, 6)
    if degraded_frac:
        attrs["degraded_frac"] = round(degraded_frac, 6)
    tracing.emit_span("train:step", start, dur, **attrs)
