"""Train-step telemetry: phase spans, per-step MFU, goodput events.

Always-cheap instrumentation for the train loop (reference intent:
ray.train's TrainingReport/metrics plumbing plus the per-step profiling
the BENCH/PROFILE rounds hand-rolled). A step is wrapped by
``ray_tpu.train.step_span()`` (or closed implicitly by ``report()``); on
completion it

- observes per-phase durations into ``ray_tpu_train_step_phase_seconds``
  (data-wait / compute / collective / checkpoint / whole step),
- computes per-step MFU from the step's FLOP count against the chip
  generation's peak (the same table bench.py normalizes with) and sets
  ``ray_tpu_train_mfu``,
- emits ``train:step`` / ``train:<phase>`` SPAN events onto the
  task-event pipeline. Rank 0's step spans are what the head folds into
  per-job **goodput** (productive step time vs. time lost to stalls and
  attempt restarts — see HeadService._train_step_event); all ranks'
  spans render as slices in ``ray_tpu timeline``.

Disable with RAY_TPU_TRAIN_TELEMETRY=0: ``step()`` then hands back a
shared no-op timer whose overhead a perf-floor test pins
(tests/test_perf_floors.py), so telemetry can never quietly tax the
train loop.
"""

from __future__ import annotations

import contextlib
import time

from ray_tpu.util.metrics import Counter, Gauge, Histogram

# Peak bf16 FLOP/s per chip by TPU generation (public spec sheets; the
# same table bench.py uses for its vs_baseline normalization).
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
}
DEFAULT_PEAK_FLOPS = 197e12

STEP_PHASE_SECONDS = Histogram(
    "ray_tpu_train_step_phase_seconds",
    "train step time by phase ('step' = the whole step)",
    boundaries=(
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0,
    ),
    tag_keys=("job", "phase"),
)
MFU_GAUGE = Gauge(
    "ray_tpu_train_mfu",
    "model FLOPs utilization of this worker's most recent step",
    tag_keys=("job",),
)
STEPS_TOTAL = Counter(
    "ray_tpu_train_steps_total",
    "completed train steps",
    tag_keys=("job",),
)
COMM_EXPOSED_RATIO = Gauge(
    "ray_tpu_train_comm_exposed_ratio",
    "fraction of the most recent step spent in collective ops NOT "
    "overlapped with compute (flight-recorder op intervals intersected "
    "with the step's compute phase) — the baseline the compute-"
    "collective overlap work must move",
    tag_keys=("job",),
)


def telemetry_enabled() -> bool:
    from ray_tpu._private import config

    return config.get("TRAIN_TELEMETRY")


def peak_flops_per_chip() -> float:
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    # tpulint: allow(broad-except reason=device probing for an MFU denominator; any jax/backend failure falls back to the documented proxy peak rather than failing the step)
    except Exception:  # noqa: BLE001 - no jax/devices: proxy peak
        return DEFAULT_PEAK_FLOPS
    for name, flops in PEAK_FLOPS.items():
        if name in kind:
            return flops
    return DEFAULT_PEAK_FLOPS


class _NoopPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NoopStepTimer:
    """Disabled path: attribute-compatible with StepTimer, shared and
    allocation-free."""

    __slots__ = ()
    phases: dict = {}
    _noop = _NoopPhase()

    def phase(self, name: str):
        return self._noop


NOOP_STEP = NoopStepTimer()


class StepTimer:
    """Measures one train step and its phases. Phase timing is a
    perf_counter pair and a dict store; span/metric emission happens
    once, at step end (finish_step)."""

    __slots__ = ("phases", "flops", "start", "_t0", "_events")

    def __init__(self, flops: float | None = None):
        self.phases: dict[str, float] = {}
        self.flops = flops
        self.start = time.time()
        self._t0 = time.perf_counter()
        # (name, wall_start, dur) per phase invocation, for timeline
        # slices placed at their true offsets.
        self._events: list[tuple[str, float, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str):
        wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            d = time.perf_counter() - t0
            self.phases[name] = self.phases.get(name, 0.0) + d
            self._events.append((name, wall, d))

    def elapsed(self) -> float:
        return time.perf_counter() - self._t0


def _merge_intervals(
    intervals: list[tuple[float, float]],
) -> list[tuple[float, float]]:
    """Union of possibly-overlapping (start, end) intervals (concurrent
    collective ops must not double-count wall time)."""
    out: list[list[float]] = []
    for s, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _overlap_seconds(
    a: list[tuple[float, float]], b: list[tuple[float, float]]
) -> float:
    """Total measure of the intersection of two MERGED interval lists."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def comm_attribution(
    step_start: float,
    step_end: float,
    compute_events: list[tuple[str, float, float]],
) -> tuple[float, float]:
    """(comm_exposed_s, comm_overlapped_s) for one step: drain the
    flight recorder's completed-op intervals, clamp them to the step
    window, and split their union by intersection with the union of the
    step's ``compute`` phase intervals. An op fully inside compute is
    overlapped (hidden behind the math); everything else is exposed
    stall. With today's serial step loop the overlap is ~0 — recorded
    honestly, which is exactly what makes it a movable baseline."""
    from ray_tpu.collective import flight_recorder

    ops = flight_recorder.take_op_intervals()
    clamped = [
        (max(s, step_start), min(e, step_end))
        for s, e in ops
        if e > step_start and s < step_end
    ]
    if not clamped:
        return 0.0, 0.0
    op_union = _merge_intervals(clamped)
    total = sum(e - s for s, e in op_union)
    compute = _merge_intervals(
        [(wall, wall + d) for name, wall, d in compute_events
         if name == "compute"]
    )
    overlapped = _overlap_seconds(op_union, compute)
    return max(0.0, total - overlapped), overlapped


def host_sync_attribution(
    step_start: float,
    step_end: float,
    compute_events: list[tuple[str, float, float]],
) -> float:
    """``host_sync_exposed_s`` for one step: drain the sanitizer's
    block_until_ready/device_get wall intervals (recorded only while
    the jax watch is installed — RAY_TPU_SANITIZE=1) and measure the
    portion inside this step's compute phase. A sync inside compute is
    a pipeline stall the hot loop paid for; syncs in the declared
    blocking phases (collective/data_wait/checkpoint) are their stated
    semantics and are not charged. The TPU601 lint pass is the static
    side of this number."""
    from ray_tpu._private import sanitize

    if not sanitize.jax_watch_active():
        return 0.0
    syncs = sanitize.take_host_sync_intervals()
    clamped = [
        (max(s, step_start), min(e, step_end))
        for s, e in syncs
        if e > step_start and s < step_end
    ]
    if not clamped:
        return 0.0
    compute = _merge_intervals(
        [(wall, wall + d) for name, wall, d in compute_events
         if name == "compute"]
    )
    return _overlap_seconds(_merge_intervals(clamped), compute)


def compute_mfu(flops: float | None, dur: float) -> float | None:
    if not flops or dur <= 0:
        return None
    try:
        import jax

        n_chips = max(1, len(jax.devices()))
    # tpulint: allow(broad-except reason=chip counting for an MFU denominator; any jax/backend failure degrades to single-chip math rather than failing the step)
    except Exception:  # noqa: BLE001
        n_chips = 1
    return flops / (dur * peak_flops_per_chip() * n_chips)


def finish_step(ctx, timer: StepTimer) -> None:
    """Close a completed step: metrics + SPAN emission + context
    bookkeeping. Called only on the step's success path — a step that
    raised must not count as productive time (its tail shows up as
    restart loss in the head's goodput accounting instead)."""
    dur = timer.elapsed()
    job = ctx.experiment_name
    STEPS_TOTAL.inc(tags={"job": job})
    STEP_PHASE_SECONDS.observe(dur, tags={"job": job, "phase": "step"})
    for ph, s in timer.phases.items():
        STEP_PHASE_SECONDS.observe(s, tags={"job": job, "phase": ph})
    mfu = compute_mfu(timer.flops, dur)
    if mfu is not None:
        MFU_GAUGE.set(mfu, tags={"job": job})
    exposed, overlapped = comm_attribution(
        timer.start, timer.start + dur, timer._events
    )
    if (exposed or overlapped) and dur > 0:
        COMM_EXPOSED_RATIO.set(exposed / dur, tags={"job": job})
    sync_exposed = host_sync_attribution(
        timer.start, timer.start + dur, timer._events
    )
    loss = getattr(timer, "loss", None)
    _emit_step_span(
        ctx, timer.start, dur, phases=dict(timer.phases), mfu=mfu,
        degraded_frac=_take_degraded_frac(ctx),
        comm_exposed_s=exposed, comm_overlapped_s=overlapped,
        host_sync_exposed_s=sync_exposed,
        loss=float(loss) if isinstance(loss, (int, float)) else None,
    )
    from ray_tpu.util import tracing

    for name, wall, d in timer._events:
        tracing.emit_span(
            f"train:{name}", wall, d,
            train_job=job, train_attempt=ctx.attempt, train_rank=ctx.rank,
        )
    ctx._step_index += 1
    ctx._used_step_timer = True
    ctx._last_report_wall = time.time()
    # Compiled-program profiler boundary: starts/advances/closes an
    # armed on-device capture (train/profile.py). Two-branch no-op
    # while disarmed (pinned by the perf-floor test); never raises.
    from ray_tpu.train import profile as _profile

    _profile.step_hook(ctx, dur)
    # Per-step memory sample (device by_kind + headroom + host RSS →
    # mem:sample span → head memory ledger). Last: it may raise the
    # RAY_TPU_FAKE_HBM_GB injected ResourceExhausted, and the step's
    # own accounting must already be closed when it does.
    from ray_tpu.runtime import memory as _mem

    _mem.step_sample(ctx)


def implicit_step(ctx, now: float, metrics: dict) -> None:
    """report()-closed step for loops that never use step_span():
    the stretch since the previous report (or loop start) is one step.
    Keeps goodput accounting working for every existing train loop."""
    base = ctx._last_report_wall or ctx._loop_start_wall
    if base is None:
        return
    dur = max(0.0, now - base)
    job = ctx.experiment_name
    STEPS_TOTAL.inc(tags={"job": job})
    STEP_PHASE_SECONDS.observe(dur, tags={"job": job, "phase": "step"})
    mfu = metrics.get("mfu") if isinstance(metrics, dict) else None
    mfu = float(mfu) if isinstance(mfu, (int, float)) else None
    if mfu is not None:
        MFU_GAUGE.set(mfu, tags={"job": job})
    phases = {}
    ckpt_s = getattr(ctx, "_last_checkpoint_s", 0.0)
    if ckpt_s:
        phases["checkpoint"] = ckpt_s
        STEP_PHASE_SECONDS.observe(
            ckpt_s, tags={"job": job, "phase": "checkpoint"}
        )
    # No phase events on the implicit path — with nothing marked as
    # compute, every collective second in the window is exposed, which
    # is the honest reading of an unannotated loop.
    exposed, overlapped = comm_attribution(base, now, [])
    if exposed and dur > 0:
        COMM_EXPOSED_RATIO.set(exposed / dur, tags={"job": job})
    loss = metrics.get("loss") if isinstance(metrics, dict) else None
    _emit_step_span(
        ctx, base, dur, phases=phases, mfu=mfu,
        degraded_frac=_take_degraded_frac(ctx),
        comm_exposed_s=exposed, comm_overlapped_s=overlapped,
        loss=float(loss) if isinstance(loss, (int, float)) else None,
    )
    ctx._step_index += 1
    from ray_tpu.runtime import memory as _mem

    _mem.step_sample(ctx)


def _take_degraded_frac(ctx) -> float:
    """Drain this step's partial-collective skip fractions into one
    degraded fraction (the worst op bounds the step: a gradient sync
    that excluded 1/4 of the ranks degrades the whole step's update by
    that fraction, however many clean ops surrounded it)."""
    fracs = getattr(ctx, "_partial_fracs", None)
    if not fracs:
        return 0.0
    frac = min(1.0, max(fracs))
    fracs.clear()
    return frac


def _emit_step_span(
    ctx, start, dur, phases, mfu, degraded_frac=0.0,
    comm_exposed_s=0.0, comm_overlapped_s=0.0,
    host_sync_exposed_s=0.0, loss=None,
) -> None:
    from ray_tpu.util import tracing

    attrs = dict(
        train_job=ctx.experiment_name,
        train_attempt=ctx.attempt,
        train_rank=ctx.rank,
        train_step=ctx._step_index,
        phases=phases,
    )
    if mfu is not None:
        attrs["mfu"] = round(mfu, 6)
    if loss is not None:
        # The sweep engine's ledger-driven schedulers read this from
        # the head's train_stats fold — report({"loss": ...}) is the
        # whole reporting path a trial needs.
        attrs["loss"] = loss
    if degraded_frac:
        attrs["degraded_frac"] = round(degraded_frac, 6)
    if comm_exposed_s or comm_overlapped_s:
        attrs["comm_exposed_s"] = round(comm_exposed_s, 6)
        attrs["comm_overlapped_s"] = round(comm_overlapped_s, 6)
    if host_sync_exposed_s:
        attrs["host_sync_exposed_s"] = round(host_sync_exposed_s, 6)
    tracing.emit_span("train:step", start, dur, **attrs)
