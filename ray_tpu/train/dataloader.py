"""TokenDataset: the training input pipeline over the native loader.

The reference's input path for large corpora is ray.data's native block
scanners; the TPU-native equivalent is a C++ mmap gather loop
(native/dataloader/dataloader.cpp) that assembles [batch, seq+1] token
batches on the host while the previous step runs on device (background
prefetch = the input-pipeline overlap the XLA scaling playbook calls
for). Sharding composes with the trainer: shard(rank, world) stripes the
shuffled window permutation across data-parallel workers.
"""

from __future__ import annotations

import numpy as np

from ray_tpu._native.dataloader import NativeTokenLoader


class TokenDataset:
    """Iterate fixed-length token windows from a flat binary corpus.

    ``path`` holds little-endian uint16 or uint32 token ids back to
    back (the standard .bin dump). Each sample is ``seq_len + 1`` tokens
    (inputs + shifted targets come from the same window).
    """

    def __init__(
        self,
        path: str,
        seq_len: int,
        *,
        dtype: str = "u32",
        seed: int = 0,
        shuffle: bool = True,
    ):
        dtype_bytes = {"u16": 2, "u32": 4}[dtype]
        self._loader = NativeTokenLoader(
            path, seq_len + 1, dtype_bytes=dtype_bytes
        )
        self.path = path
        self.dtype = dtype
        self.seq_len = seq_len
        self.seed = seed
        self.shuffle = shuffle
        self._rank, self._world = 0, 1
        self._epoch = 0

    def descriptor(self) -> dict:
        """Picklable spec: workers re-open their own mmap (loaders hold
        fds/threads and must not cross process boundaries). Used by
        JaxTrainer(datasets=...) sharding."""
        return {
            "__token_dataset__": {
                "path": self.path,
                "seq_len": self.seq_len,
                "dtype": self.dtype,
                "seed": self.seed,
                "shuffle": self.shuffle,
            }
        }

    @classmethod
    def from_descriptor(
        cls, desc: dict, rank: int = 0, world: int = 1
    ) -> "TokenDataset":
        spec = desc["__token_dataset__"]
        ds = cls(
            spec["path"],
            spec["seq_len"],
            dtype=spec["dtype"],
            seed=spec["seed"],
            shuffle=spec["shuffle"],
        )
        if world > 1:
            ds.shard(rank, world)
        return ds

    @property
    def num_samples(self) -> int:
        return self._loader.num_windows // self._world

    def shard(self, rank: int, world: int) -> "TokenDataset":
        """Restrict this dataset to a data-parallel shard (reference:
        DataConfig splits streams per train worker,
        train/v2/_internal/data_integration/)."""
        self._rank, self._world = rank, world
        self._loader.set_shard(rank, world)
        return self

    def iter_batches(self, batch_size: int, *, epochs: int = 1):
        """Yield {"tokens": [B, seq+1] uint32} with background prefetch;
        the tail partial batch of each epoch is dropped (static shapes
        for jit)."""
        # Every rank yields EXACTLY this many batches per epoch (ranks
        # can differ by one window; an uneven batch count would hang
        # synchronized SPMD training at the epoch boundary).
        batches_per_epoch = self.num_samples // batch_size
        for _ in range(epochs):
            if self.shuffle:
                # Same seed on every shard → one global permutation,
                # disjoint stripes per rank.
                self._loader.shuffle(self.seed + self._epoch)
            self._loader.prefetch_start(batch_size)
            try:
                for _i in range(batches_per_epoch):
                    batch = self._loader.next()
                    if len(batch) < batch_size:
                        break  # defensive: loader exhausted early
                    yield {"tokens": batch}
            finally:
                self._loader.prefetch_stop()
            self._epoch += 1

    def take_batch(self, batch_size: int, start: int = 0) -> dict:
        """Synchronous gather (no prefetch thread) — handy for eval."""
        return {"tokens": self._loader.fill(start, batch_size)}

    def close(self) -> None:
        self._loader.close()
