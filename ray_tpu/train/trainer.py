"""JaxTrainer: controller + worker-group training (Train v2 architecture).

Mirrors the reference's Train v2 control plane (reference:
python/ray/train/v2/api/data_parallel_trainer.py:66 `fit` :154 →
TrainController controller.py:103 → WorkerGroup worker_group.py:112 on a
placement group → per-framework Backend.on_start; JaxTrainer
python/ray/train/v2/jax/jax_trainer.py:19 with jax.distributed bootstrap
config.py:32). Differences, deliberately TPU-first:

- The worker group reserves a *slice-shaped* placement group (one bundle
  per worker) so a multi-host TPU slice is the scheduling unit.
- The backend hands each worker the jax.distributed coordinator through
  the cluster KV (same rendezvous as the collective layer) instead of a
  torch process group.
- Failure policy: a slice is atomic — any worker death fails the whole
  attempt; the controller re-creates the group and restores from the
  latest checkpoint (reference: failure_policy.py RETRY semantics).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

import logging

import ray_tpu
from ray_tpu.exceptions import RayTpuError
from ray_tpu.placement import placement_group, remove_placement_group
from ray_tpu.train.session import TrainContext, _set_context

logger = logging.getLogger("ray_tpu.train")


@dataclass
class ScalingConfig:
    """(reference: ray.train.ScalingConfig incl. the TPU fields
    use_tpu/topology in the JaxTrainer docstring jax_trainer.py:50)"""

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: dict = field(default_factory=dict)
    topology: str | None = None
    placement_strategy: str = "PACK"
    # Form ONE global jax mesh across all workers: every worker runs
    # jax.distributed.initialize (KV-rendezvous'd through the head)
    # before the train loop, so jax.devices() spans the worker group
    # (reference: _JaxBackend v2/jax/config.py:32-96 does this per
    # worker). Required for FSDP/TP across hosts; off for independent
    # per-worker DP loops.
    distributed: bool = False
    # Deadline for the worker group's collective ops and rendezvous
    # (None = config COLLECTIVE_TIMEOUT_S). A member lost mid-step then
    # surfaces as a typed collective abort within this bound, which the
    # controller turns into an elastic resize instead of a hang.
    collective_timeout_s: float | None = None
    # Straggler-tolerant gradient sync: with allow_partial_grads on, the
    # train loop's session.partial_collective_opts() maps to
    # allreduce(min_ranks=ceil(world * partial_min_fraction),
    # grace_s=partial_grace_s) — a slow host costs the step a bounded,
    # rescaled skip (charged to the goodput ledger as "degraded") instead
    # of stalling the world; chronic skips escalate into the
    # drain-and-replace path. partial_grace_s None = config
    # COLLECTIVE_PARTIAL_GRACE_S.
    allow_partial_grads: bool = False
    partial_min_fraction: float = 0.75
    partial_grace_s: float | None = None
    # Compressed gradient sync: grad_compression="int8" makes
    # session.grad_sync_opts() request the block-scaled int8 codec on
    # the gradient allreduce (~3.9x fewer wire bytes, fp32
    # accumulation — see ray_tpu/collective/codec.py). Composes with
    # allow_partial_grads: the compressed program carries the partial
    # mask. None keeps gradient sync byte-identical to today.
    grad_compression: str | None = None
    # Bucketed overlap gradient sync (T3-style): with grad_overlap on,
    # session.grad_sync_opts() reports overlap=True and the step loop
    # issues per-bucket async allreduces (collective/bucketer.py)
    # eagerly — in reverse-layer order, ~grad_bucket_mb MiB per bucket
    # (None = config COLLECTIVE_BUCKET_MB), per-bucket ring/tree
    # selection by size — joining the handles just before the optimizer
    # update so the collectives hide behind remaining compute.
    # grad_error_feedback carries each bucket's int8 quantization
    # residual into the next step (needs grad_compression).
    grad_overlap: bool = False
    grad_bucket_mb: float | None = None
    grad_error_feedback: bool = False
    # ZeRO-sharded weight update (arXiv:2004.13336): with zero_sharding
    # on, session.grad_sync_opts() reports zero=True and the step loop
    # flips gradient sync from allreduce → full update on every rank to
    # reduce-scatter → shard-local optimizer update (train/zero.py,
    # ~1/world of the adamw state resident per rank — the BENCH_8B
    # capacity wall) → allgather updated weights. Leaf ownership is the
    # checkpoint manifest's round-robin partition, so saving the
    # sharded state via AsyncCheckpointer(local_prefixes=
    # (zero.CKPT_PREFIX,)) needs no gather. Composes with
    # grad_compression (+error feedback) and allow_partial_grads on
    # the reduce hop; the gather hop ships exact weights, all-N.
    zero_sharding: bool = False

    def bundle(self) -> dict:
        b = {"CPU": 1.0}
        b.update(self.resources_per_worker)
        if self.use_tpu and self.chips_per_worker:
            b["TPU"] = float(self.chips_per_worker)
        return b


@dataclass
class FailureConfig:
    max_failures: int = 0


class ScalingPolicy:
    """Decides each attempt's worker-group size (reference:
    train/v2/_internal/execution/scaling_policy/scaling_policy.py).
    The default keeps the configured size: a failed attempt retries at
    full width. ``last_error`` carries the previous attempt's failure —
    a CollectiveError (member death / op timeout) is the resize trigger
    the collective layer surfaces to elastic policies."""

    def workers_for_attempt(
        self,
        scaling: "ScalingConfig",
        attempt: int,
        cluster_free: list[dict],
        last_error: Exception | None = None,
    ) -> int:
        del attempt, cluster_free, last_error
        return scaling.num_workers


class ElasticScalingPolicy(ScalingPolicy):
    """Re-fit the worker group to what the cluster can actually place.

    A TPU slice is atomic — losing one host loses the whole slice — so
    after a failure the next attempt resizes to however many worker
    bundles still fit (floor min_workers), restoring from the latest
    checkpoint instead of waiting for the dead slice to come back
    (SURVEY.md §7 hard parts; reference resize semantics:
    scaling_policy.py + slice-atomic failure handling)."""

    def __init__(self, min_workers: int = 1):
        if min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        self.min_workers = min_workers

    def workers_for_attempt(
        self,
        scaling: "ScalingConfig",
        attempt: int,
        cluster_free: list[dict],
        last_error: Exception | None = None,
    ) -> int:
        del last_error  # any failure re-fits; the kind only affects settle
        if attempt == 0:
            return scaling.num_workers
        bundle = scaling.bundle()
        spread = scaling.placement_strategy in ("SPREAD", "STRICT_SPREAD")
        fit = 0
        for avail in cluster_free:
            # Slice-labeled nodes count by WHOLE SURVIVING SLICES, not
            # bundles: a slice with a draining/dead sibling is atomic —
            # its survivors die with it (GCE reaps the slice as a unit),
            # so bundles placed there would size an attempt that loses
            # them mid-rendezvous. _cluster_free marks members of such
            # slices with _slice_whole=False.
            if avail.get("_slice") is not None and not avail.get(
                "_slice_whole", True
            ):
                continue
            per_node = min(
                (
                    int(avail.get(k, 0.0) // v)
                    for k, v in bundle.items()
                    if v > 0
                ),
                default=0,
            )
            # STRICT_SPREAD needs a distinct node per bundle; counting
            # stacked bundles would size an infeasible attempt.
            fit += min(per_node, 1) if spread else per_node
        return max(self.min_workers, min(scaling.num_workers, fit))


@dataclass
class RunConfig:
    name: str = "train_run"
    storage_path: str = "/tmp/ray_tpu_results"
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    # Sweep-engine trial scoping (tune/sweep.py sets these): carried
    # into every worker's TrainContext so telemetry and chaos tooling
    # can attribute a gang to its trial across migrations.
    sweep_id: str | None = None
    trial_id: str | None = None
    # Seed the resume path before the FIRST attempt: a checkpoint path
    # or ckpt:// URI, or "auto" to discover the run's newest valid
    # checkpoint (file dir or in-cluster shard store). "auto" is how a
    # PBT-forked trial restores the manifest forked into its run name.
    resume_from_checkpoint: str | None = None


@dataclass
class Result:
    metrics: dict
    checkpoint: str | None
    path: str
    error: Exception | None = None


@ray_tpu.remote
class TrainWorker:
    """One member of the worker group (reference: worker_group.py actors)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.ctx: TrainContext | None = None

    def setup(
        self,
        experiment_name: str,
        storage_path: str,
        config: dict,
        latest_checkpoint: str | None,
        backend_env: dict,
        dataset_shards: dict | None = None,
    ):
        import os

        # The JAX platform override must be applied by this process (the
        # node manager only bakes env into *newly spawned* workers): an
        # empty value means "let jax pick the TPU runtime", anything else
        # pins the named platform.
        jax_platform = backend_env.pop("RAY_TPU_WORKER_JAX_PLATFORMS", None)
        os.environ.update(backend_env)
        if jax_platform is not None:
            if jax_platform:
                os.environ["JAX_PLATFORMS"] = jax_platform
            else:
                os.environ.pop("JAX_PLATFORMS", None)
        # RAY_TPU_SANITIZE=1: install the jit-discipline twins (compile
        # watch + host-sync tracer) BEFORE any jax.jit in this process,
        # so the flagship train step itself is under the watch.
        from ray_tpu._private import sanitize as _sanitize

        _sanitize.maybe_install_jax_watch()
        # Watch the head's drain fan-out (the PR-1 death channel): a
        # preemption notice for any node must reach this worker BEFORE
        # the node dies so the loop can take its emergency checkpoint
        # at the next step boundary (train.preemption_notice()).
        try:
            import ray_tpu.collective as _col

            rt = ray_tpu.api._runtime
            rt.run(_col._ensure_death_watch(rt.core))
        except Exception:  # noqa: BLE001 - client-mode / degraded head
            logger.debug(
                "drain fan-out subscription unavailable; training "
                "continues without the preemption notice window",
                exc_info=True,
            )
        collective_group = ""
        attempt = int(backend_env.get("RAY_TPU_TRAIN_ATTEMPT", "0"))
        col_timeout = backend_env.get("RAY_TPU_TRAIN_COLLECTIVE_TIMEOUT_S")
        col_timeout = float(col_timeout) if col_timeout else None
        if backend_env.get("RAY_TPU_TRAIN_DISTRIBUTED") == "1":
            # One global mesh across the worker group: bootstrap
            # jax.distributed through the head-KV rendezvous BEFORE any
            # jax computation in this process (reference: _JaxBackend
            # config.py:84 jax.distributed.initialize per worker). The
            # group doubles as an eager-collective group
            # (session.collective_group_name()). The name is
            # ATTEMPT-scoped so a retry never rendezvouses with a dead
            # previous attempt's coordinator KV entry.
            from ray_tpu import collective as col

            collective_group = f"train:{experiment_name}:a{attempt}"
            if not col.is_group_initialized(collective_group):
                col.init_collective_group(
                    self.world_size,
                    self.rank,
                    backend="xla_dist",
                    group_name=collective_group,
                    timeout_s=col_timeout,
                )
        partial_grace = backend_env.get("RAY_TPU_TRAIN_PARTIAL_GRACE_S")
        grad_compression = (
            backend_env.get("RAY_TPU_TRAIN_GRAD_COMPRESSION") or None
        )
        grad_bucket_mb = backend_env.get("RAY_TPU_TRAIN_GRAD_BUCKET_MB")
        # The slice fault domain this worker dies with: its node's
        # "slice" label (None off-slice). Resolved once at setup so the
        # loop (and the SLICE_FAIL chaos knob) never pays a head RPC
        # per step.
        slice_label = None
        try:
            rt = ray_tpu.api._runtime
            node_addr = getattr(rt.core, "node_addr", None)
            if node_addr:
                table = rt.run(rt.core.head.call("node_table"), 5)
                for n in table.values():
                    if n.get("addr") == node_addr:
                        slice_label = (n.get("labels") or {}).get("slice")
                        break
        # tpulint: allow(broad-except reason=client-mode / degraded head: a worker without a resolvable slice simply has no slice fault domain)
        except Exception:
            slice_label = None
        self.ctx = TrainContext(
            world_size=self.world_size,
            rank=self.rank,
            experiment_name=experiment_name,
            storage_path=storage_path,
            latest_checkpoint=latest_checkpoint,
            config=config,
            dataset_shards=dataset_shards or {},
            collective_group=collective_group,
            attempt=attempt,
            allow_partial_grads=(
                backend_env.get("RAY_TPU_TRAIN_PARTIAL_GRADS") == "1"
            ),
            partial_min_fraction=float(
                backend_env.get("RAY_TPU_TRAIN_PARTIAL_MIN_FRACTION", "0.75")
            ),
            partial_grace_s=float(partial_grace) if partial_grace else None,
            grad_compression=grad_compression,
            grad_overlap=(
                backend_env.get("RAY_TPU_TRAIN_GRAD_OVERLAP") == "1"
            ),
            grad_bucket_mb=(
                float(grad_bucket_mb) if grad_bucket_mb else None
            ),
            grad_error_feedback=(
                backend_env.get("RAY_TPU_TRAIN_GRAD_ERROR_FEEDBACK") == "1"
            ),
            zero_sharding=(
                backend_env.get("RAY_TPU_TRAIN_ZERO_SHARDING") == "1"
            ),
            slice_label=slice_label,
            sweep_id=backend_env.get("RAY_TPU_TRAIN_SWEEP_ID") or None,
            trial_id=backend_env.get("RAY_TPU_TRAIN_TRIAL_ID") or None,
        )
        return True

    def run_loop(self, train_loop: Callable, use_context_arg: bool):
        from ray_tpu.util import tracing

        _set_context(self.ctx)
        # Anchor for the first implicit step (report() with no explicit
        # step_span) and for the attempt span below.
        attempt_start = time.time()
        self.ctx._loop_start_wall = attempt_start
        try:
            if use_context_arg:
                train_loop(self.ctx.config)
            else:
                train_loop()
        except Exception as e:
            # OOM forensics: a ResourceExhausted (real backend OOM or
            # the RAY_TPU_FAKE_HBM_GB injection) must answer "what ate
            # the HBM" before the attempt dies — ranked live-buffer
            # report as a mem:oom span + persisted JSON (idempotent:
            # the injection path may have already filed it).
            from ray_tpu.runtime import memory as _mem

            if _mem.is_resource_exhausted(e):
                try:
                    _mem.on_resource_exhausted(
                        e, job=self.ctx.experiment_name
                    )
                # tpulint: allow(broad-except reason=forensics on an attempt that is already dying of OOM; the OOM is the error that must propagate)
                except Exception:  # noqa: BLE001
                    logger.debug("OOM forensics failed", exc_info=True)
            # Collective abort (a group member died / an op timed out
            # mid-step): tear down this worker's groups so their pending
            # futures fail instead of leaking, then fail the attempt —
            # the controller surfaces the abort to the scaling policy as
            # a resize trigger and restores from the last checkpoint.
            from ray_tpu.collective.types import CollectiveError

            if isinstance(e, CollectiveError):
                import ray_tpu.collective as col

                for name in list(col._groups):
                    try:
                        col.destroy_collective_group(name)
                    # tpulint: allow(broad-except reason=group teardown while the attempt is already failing on a collective abort; the original abort is the error that propagates)
                    except Exception:  # noqa: BLE001 - teardown best-effort
                        pass
            raise
        finally:
            _set_context(None)
            # Attempt-end checkpoint barrier: an async persist still in
            # flight must commit (or fail) before the controller kills
            # this worker, or the attempt's last checkpoint is lost.
            try:
                from ray_tpu import checkpoint as _dist_ckpt

                _dist_ckpt.wait_pending(timeout=30.0)
            # tpulint: allow(broad-except reason=persist failures are already logged by the saver thread; the attempt outcome must not change because its LAST checkpoint failed — resume just uses an older one)
            except Exception:
                pass
            # One slice per controller attempt in the timeline: restart
            # churn is visible as gaps between attempt spans.
            tracing.emit_span(
                "train:attempt",
                attempt_start,
                time.time() - attempt_start,
                train_job=self.ctx.experiment_name,
                train_attempt=self.ctx.attempt,
                train_rank=self.ctx.rank,
            )
            # The controller kills this worker right after the attempt
            # resolves — flush now or the attempt's last second of
            # spans/metrics (the goodput boundary) dies with it.
            try:
                import asyncio as _asyncio

                rt = ray_tpu.api._runtime
                if rt.core is not None:
                    rt.run(
                        _asyncio.wait_for(
                            rt.core.flush_observability(), 5.0
                        )
                    )
            except Exception:  # noqa: BLE001 - flush is best-effort
                logger.debug(
                    "attempt-end observability flush failed", exc_info=True
                )
        return {
            "rank": self.rank,
            "reports": self.ctx.reports,
            "latest_metrics": self.ctx.latest_metrics,
        }


class JaxTrainer:
    """Data-parallel / FSDP JAX training over a gang-scheduled worker
    group. The user's ``train_loop_per_worker`` builds its mesh with
    ray_tpu.parallel.make_mesh and shards with the rule table — the
    trainer owns process placement, rendezvous env, checkpoints, and
    retries; XLA owns the collectives."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        scaling_policy: ScalingPolicy | None = None,
        datasets: dict | None = None,
    ):
        self.train_loop = train_loop_per_worker
        self.config = train_loop_config or {}
        self.scaling = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.scaling_policy = scaling_policy or ScalingPolicy()
        # name → ray_tpu.data.Dataset; split per worker at fit() time
        # (reference: DataConfig splits ray.data streams per worker,
        # train/v2/_internal/data_integration/).
        self.datasets = datasets or {}
        # Sweep-engine stop hook: request_stop() kills the current
        # attempt's gang and makes fit() return (latest checkpoint,
        # no error) instead of retrying — an ASHA rung kill must not
        # fight the controller's own failure policy.
        self._stop_requested = False
        self._live_workers: list = []

    def request_stop(self) -> None:
        """Stop this trainer from another thread: the current gang is
        killed and fit() returns its latest checkpoint without
        retrying. Idempotent; safe before fit() starts (the first
        attempt is then skipped)."""
        self._stop_requested = True
        for w in list(self._live_workers):
            try:
                ray_tpu.kill(w)
            except RayTpuError:
                pass

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested

    def _split_datasets(self, n: int) -> list[dict]:
        """Materialize each dataset and deal its block refs round-robin:
        worker i gets shard dicts {name: [refs]} — refs resolve from any
        process (ownership model), so shards ship as plain messages.
        TokenDatasets (native file loaders) ship as descriptors instead:
        each worker re-opens its own mmap and takes a (rank, world)
        stripe of the shuffled permutation."""
        from ray_tpu.train.dataloader import TokenDataset

        shards: list[dict] = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if isinstance(ds, TokenDataset):
                desc = ds.descriptor()
                for i in range(n):
                    shards[i][name] = {**desc, "rank": i, "world": n}
                continue
            refs = ds.materialize()._refs
            for i in range(n):
                shards[i][name] = refs[i::n]
        return shards

    # ------------------------------------------------------------ fit
    def fit(self) -> Result:
        failures = 0
        resume = self.run_config.resume_from_checkpoint
        latest_checkpoint: str | None = (
            self._find_latest_checkpoint() if resume == "auto" else resume
        )
        last_err: Exception | None = None
        while not self._stop_requested:
            n = self._policy_workers(failures, last_err)
            try:
                return self._run_attempt(latest_checkpoint, failures, n)
            except Exception as e:  # noqa: BLE001 - controller retry loop
                if self._stop_requested:
                    # The attempt died because request_stop() killed the
                    # gang — that is a clean stop, not a failure.
                    latest_checkpoint = (
                        self._find_latest_checkpoint() or latest_checkpoint
                    )
                    last_err = None
                    break
                logger.warning(
                    "train attempt %d failed (%s: %s); %s",
                    failures,
                    type(e).__name__,
                    e,
                    "retrying"
                    if failures < self.run_config.failure_config.max_failures
                    else "out of retries",
                )
                last_err = e
                failures += 1
                latest_checkpoint = (
                    self._find_latest_checkpoint() or latest_checkpoint
                )
                if failures > self.run_config.failure_config.max_failures:
                    break
                self._settle_cluster_view(e)
        return Result(
            metrics={},
            checkpoint=latest_checkpoint,
            path=self._run_dir(),
            error=last_err,
        )

    def _policy_workers(
        self, attempt: int, last_err: Exception | None
    ) -> int:
        try:
            return self.scaling_policy.workers_for_attempt(
                self.scaling,
                attempt,
                self._cluster_free(),
                last_error=last_err,
            )
        except TypeError:
            # User policy predating the last_error hook.
            return self.scaling_policy.workers_for_attempt(
                self.scaling, attempt, self._cluster_free()
            )

    @staticmethod
    def _is_preemption(err: Exception | None) -> bool:
        """Did the attempt unwind on a drain-notice emergency checkpoint
        (PreemptedError)? Like collective aborts, the failure is
        *detected*, not inferred — the retry can size and start as soon
        as the node table holds still."""
        from ray_tpu.exceptions import PreemptedError

        seen = 0
        while err is not None and seen < 8:
            if isinstance(err, PreemptedError) or "PreemptedError" in str(
                err
            ):
                return True
            err = getattr(err, "cause", None) or err.__cause__
            seen += 1
        return False

    @staticmethod
    def _is_collective_abort(err: Exception | None) -> bool:
        """Did the attempt fail on a typed collective abort? Checks the
        exception and its carried causes — worker errors arrive wrapped
        in RayTaskError with the original in .cause (or stringified when
        unpicklable)."""
        from ray_tpu.collective.types import CollectiveError

        seen = 0
        while err is not None and seen < 8:
            if isinstance(err, CollectiveError):
                return True
            if any(
                name in str(err)
                for name in (
                    "CollectiveTimeoutError",
                    "CollectiveMemberDiedError",
                )
            ):
                return True
            err = getattr(err, "cause", None) or err.__cause__
            seen += 1
        return False

    def _settle_cluster_view(self, err: Exception | None) -> None:
        """Let the cluster view settle before sizing the retry.

        Default failure (a hang inferred from worker death): the dead
        slice must age out of the node table (HEALTH_TIMEOUT_S) and
        survivors' heartbeats must republish bundles freed by the failed
        attempt's PG — wait the full window.

        Collective abort: the failure was *detected*, and the abort path
        already probed the head (collective_probe removes a confirmed-
        dead node immediately), so poll until the node table holds still
        instead of sleeping the worst case."""
        from ray_tpu._private import config as _config

        budget = _config.get("HEALTH_TIMEOUT_S") + 2.0
        if not (self._is_collective_abort(err) or self._is_preemption(err)):
            time.sleep(budget)
            return
        deadline = time.monotonic() + budget
        prev: frozenset | None = None
        stable = 0
        while time.monotonic() < deadline:
            try:
                rt = ray_tpu.api._runtime
                status = rt.run(rt.core.head.call("cluster_status"))
                view = frozenset(status.get("nodes", {}).keys())
            # tpulint: allow(broad-except reason=the head may be mid-restart during settle; an unreadable view just means "not stable yet" and the loop keeps polling inside its deadline)
            except Exception:  # noqa: BLE001 - head busy: keep waiting
                view = None
            stable = stable + 1 if view is not None and view == prev else 0
            prev = view
            if stable >= 3:
                return
            time.sleep(0.5)

    def _cluster_free(self) -> list[dict]:
        """Per-live-node available resources (the scaling policy's view
        of what an attempt can place). Draining nodes are excluded —
        counting a preempting node's capacity would size an attempt the
        placement layer can no longer satisfy. Slice-labeled nodes
        additionally carry ``_slice`` (the fault-domain id) and
        ``_slice_whole`` (False when ANY sibling of the slice is
        draining/dead/unhealthy): a slice dies as a unit, so the
        elastic policy must count whole surviving slices, not the
        stray healthy bundles of a condemned one."""
        try:
            rt = ray_tpu.api._runtime
            status = rt.run(rt.core.head.call("cluster_status"))
            draining = set(status.get("draining") or {})
            node_slice: dict[str, str] = {}
            whole: dict[str, bool] = {}
            for sid, rec in (status.get("slices") or {}).items():
                members = list(rec.get("nodes") or [])
                for nid in members:
                    node_slice[nid] = sid
                whole[sid] = (
                    rec.get("state") == "healthy"
                    and not any(nid in draining for nid in members)
                )
            out = []
            for nid, n in status.get("nodes", {}).items():
                if nid in draining:
                    continue
                avail = dict(n.get("available", {}))
                sid = node_slice.get(nid)
                if sid is not None:
                    avail["_slice"] = sid
                    avail["_slice_whole"] = whole.get(sid, False)
                out.append(avail)
            return out
        except Exception:  # noqa: BLE001 - policy falls back to config
            logger.debug(
                "cluster_status unavailable; scaling policy sees an "
                "empty free list", exc_info=True,
            )
            return []

    def _run_dir(self) -> str:
        import os

        return os.path.join(
            self.run_config.storage_path, self.run_config.name
        )

    def _find_latest_checkpoint(self) -> str | None:
        """Newest VALID checkpoint for the resume path: the newest
        non-empty report()-persisted dir (a dying attempt can leave a
        half-copied or empty newest dir behind — fall back to the
        previous entry, the restore_latest_valid semantics), else the
        newest COMPLETE in-cluster shard-store checkpoint for this run
        as a ``ckpt://`` URI — so a cluster with no shared checkpoint
        directory still resumes from replicas."""
        import os

        from ray_tpu.train.checkpoint import list_checkpoint_dirs

        d = self._run_dir()
        for _idx, name in reversed(list_checkpoint_dirs(d)):
            path = os.path.join(d, name)
            try:
                if os.path.isdir(path) and os.listdir(path):
                    return path
            except OSError:
                continue
        return self._latest_store_checkpoint()

    def _latest_store_checkpoint(self) -> str | None:
        """Newest complete shard-store checkpoint URI for this run (the
        head's manifest table), or None (also on a degraded head — the
        resume path must never fail the controller)."""
        try:
            from ray_tpu import checkpoint as dist_ckpt

            step = dist_ckpt.latest_step(self.run_config.name)
            if step is not None:
                return dist_ckpt.make_uri(self.run_config.name, step)
        # tpulint: allow(broad-except reason=resume discovery must never fail the controller; a degraded/absent head just means no store checkpoint to offer)
        except Exception:
            pass
        return None

    def _backend_env(
        self, rank: int, attempt: int = 0, n_workers: int | None = None
    ) -> dict:
        """Worker env for the JAX backend (reference: _JaxBackend
        v2/jax/config.py:32 _setup_jax_distributed_environment)."""
        n = n_workers or self.scaling.num_workers
        env = {
            "RAY_TPU_TRAIN_RANK": str(rank),
            "RAY_TPU_TRAIN_WORLD": str(n),
        }
        if self.scaling.topology:
            env["TPU_TOPOLOGY"] = self.scaling.topology
        if self.scaling.use_tpu:
            # TPU workers own the chip runtime; everything else stays on
            # the JAX CPU backend so it never contends for the slice.
            env["RAY_TPU_WORKER_JAX_PLATFORMS"] = ""
        # Attempt is always exposed (not only for distributed) so train
        # loops can scope their own collective groups per attempt.
        env["RAY_TPU_TRAIN_ATTEMPT"] = str(attempt)
        if self.run_config.sweep_id:
            env["RAY_TPU_TRAIN_SWEEP_ID"] = self.run_config.sweep_id
        if self.run_config.trial_id:
            env["RAY_TPU_TRAIN_TRIAL_ID"] = self.run_config.trial_id
        if self.scaling.collective_timeout_s is not None:
            env["RAY_TPU_TRAIN_COLLECTIVE_TIMEOUT_S"] = str(
                self.scaling.collective_timeout_s
            )
        if self.scaling.allow_partial_grads:
            env["RAY_TPU_TRAIN_PARTIAL_GRADS"] = "1"
            env["RAY_TPU_TRAIN_PARTIAL_MIN_FRACTION"] = str(
                self.scaling.partial_min_fraction
            )
            if self.scaling.partial_grace_s is not None:
                env["RAY_TPU_TRAIN_PARTIAL_GRACE_S"] = str(
                    self.scaling.partial_grace_s
                )
        if self.scaling.grad_compression:
            env["RAY_TPU_TRAIN_GRAD_COMPRESSION"] = str(
                self.scaling.grad_compression
            )
        if self.scaling.grad_overlap:
            env["RAY_TPU_TRAIN_GRAD_OVERLAP"] = "1"
        if self.scaling.grad_bucket_mb is not None:
            env["RAY_TPU_TRAIN_GRAD_BUCKET_MB"] = str(
                self.scaling.grad_bucket_mb
            )
        if self.scaling.grad_error_feedback:
            env["RAY_TPU_TRAIN_GRAD_ERROR_FEEDBACK"] = "1"
        if self.scaling.zero_sharding:
            env["RAY_TPU_TRAIN_ZERO_SHARDING"] = "1"
        if self.scaling.distributed and n > 1:
            env["RAY_TPU_TRAIN_DISTRIBUTED"] = "1"
        return env

    def _run_attempt(
        self,
        latest_checkpoint: str | None,
        attempt: int = 0,
        n_workers: int | None = None,
    ) -> Result:
        n = n_workers or self.scaling.num_workers
        pg = placement_group(
            [self.scaling.bundle() for _ in range(n)],
            strategy=self.scaling.placement_strategy,
        )
        workers = []
        try:
            workers = [
                TrainWorker.options(
                    placement_group=pg,
                    placement_group_bundle_index=i,
                    # Request what the bundle reserved: a non-default
                    # resources_per_worker (fractional CPUs, TPU chips)
                    # must be leased by the worker actor itself, not
                    # just held by the bundle.
                    resources=self.scaling.bundle(),
                ).remote(i, n)
                for i in range(n)
            ]
            self._live_workers = workers
            shards = self._split_datasets(n)
            ray_tpu.get(
                [
                    w.setup.remote(
                        self.run_config.name,
                        self.run_config.storage_path,
                        self.config,
                        latest_checkpoint,
                        self._backend_env(i, attempt, n),
                        shards[i],
                    )
                    for i, w in enumerate(workers)
                ],
                timeout=60,
            )
            import inspect

            use_arg = len(inspect.signature(self.train_loop).parameters) > 0
            refs = [
                w.run_loop.remote(self.train_loop, use_arg) for w in workers
            ]
            results = ray_tpu.get(refs)
            rank0 = next(r for r in results if r["rank"] == 0)
            return Result(
                metrics=rank0["latest_metrics"],
                checkpoint=self._find_latest_checkpoint(),
                path=self._run_dir(),
            )
        finally:
            self._live_workers = []
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except RayTpuError:
                    pass
            remove_placement_group(pg)
            time.sleep(0.1)  # let worker teardown settle before re-slicing
