"""Per-worker training session: get_context() / report() from inside the
user's train loop (reference: ray.train.report and
ray.train.get_context(), python/ray/train/v2/_internal/execution/context).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import time
from dataclasses import dataclass, field
from typing import Any


@dataclass
class TrainContext:
    world_size: int = 1
    rank: int = 0
    experiment_name: str = "default"
    storage_path: str = ""
    latest_checkpoint: str | None = None
    config: dict = field(default_factory=dict)
    # name → list of block ObjectRefs (this worker's split)
    dataset_shards: dict = field(default_factory=dict)
    # eager-collective group formed by the trainer backend (empty when
    # ScalingConfig.distributed is off); attempt-scoped name
    collective_group: str = ""
    # controller retry attempt this worker belongs to (0 on the first
    # try) — lets a train loop scope its own collective-group names per
    # attempt so a retry never rendezvouses with a dead attempt's KV keys
    attempt: int = 0
    # Straggler-tolerant gradient sync (ScalingConfig.allow_partial_grads
    # threads these through): partial_collective_opts() turns them into
    # the allreduce(min_ranks=, grace_s=) kwargs for the train loop.
    allow_partial_grads: bool = False
    partial_min_fraction: float = 0.75
    partial_grace_s: float | None = None
    # Compressed gradient sync (ScalingConfig.grad_compression): the
    # codec name grad_sync_opts() forwards to the gradient collective
    # ("int8" = block-scaled int8 wire format, fp32 accumulation).
    grad_compression: str | None = None
    # Bucketed overlap gradient sync (ScalingConfig.grad_overlap /
    # grad_bucket_mb / grad_error_feedback): grad_sync_opts() reports
    # overlap=True and grad_bucketer() hands the loop a configured
    # collective.bucketer.GradBucketer (cached per attempt).
    grad_overlap: bool = False
    grad_bucket_mb: float | None = None
    grad_error_feedback: bool = False
    _grad_bucketer: object = None
    # ZeRO-sharded weight update (ScalingConfig.zero_sharding,
    # arXiv:2004.13336): grad_sync_opts() reports zero=True and the
    # step loop flips from allreduce-then-full-update to
    # reduce-scatter → zero_optimizer().apply → allgather weights,
    # holding ~1/world of the optimizer state resident per rank.
    zero_sharding: bool = False
    _zero_optimizer: object = None
    # This worker's node "slice" label (None off-slice): the fault
    # domain it dies with. Resolved by TrainWorker.setup through the
    # head node table; the RAY_TPU_SLICE_FAIL chaos knob and slice-
    # aware train loops read it via train.slice_label().
    slice_label: str | None = None
    # Sweep-engine trial scoping (tune/sweep.py): the sweep and trial
    # this worker's gang belongs to (None outside a sweep). Threaded
    # from RunConfig through the backend env so a migrated gang keeps
    # its identity across attempts and nodes.
    sweep_id: str | None = None
    trial_id: str | None = None
    # mutated by report():
    reports: list = field(default_factory=list)
    latest_metrics: dict = field(default_factory=dict)
    # step-telemetry bookkeeping (train/telemetry.py): set by the
    # trainer at loop start / mutated as steps close
    _loop_start_wall: float | None = None
    _last_report_wall: float | None = None
    _last_checkpoint_s: float = 0.0
    _step_index: int = 0
    _used_step_timer: bool = False
    # skipped-rank fractions of this step's partial collectives; drained
    # into the step span's degraded_frac by telemetry at step close
    _partial_fracs: list = field(default_factory=list)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank


_context: TrainContext | None = None


def _set_context(ctx: TrainContext | None):
    global _context
    _context = ctx


def get_context() -> TrainContext:
    if _context is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() is only valid inside a train loop"
        )
    return _context


def collective_group_name() -> str:
    """Name of the worker group's eager collective group (initialized by
    the trainer when ScalingConfig(distributed=True)); pass to
    ray_tpu.collective verbs inside the train loop."""
    name = get_context().collective_group
    if not name:
        raise RuntimeError(
            "no collective group: the trainer was not started with "
            "ScalingConfig(distributed=True)"
        )
    return name


def get_checkpoint() -> str | None:
    """Latest checkpoint directory to restore from (None on fresh start)."""
    return get_context().latest_checkpoint


def partial_collective_opts(world: int | None = None) -> dict:
    """The ``allreduce(min_ranks=, grace_s=)`` kwargs this worker group
    was configured for (``ScalingConfig(allow_partial_grads=True,
    partial_min_fraction=, partial_grace_s=)``), or ``{}`` when partial
    gradient sync is off — so train loops can write
    ``col.allreduce(grads, **train.partial_collective_opts())``
    unconditionally. ``world`` defaults to the worker-group size; pass
    the collective group's world when they differ."""
    import math

    ctx = get_context()
    if not ctx.allow_partial_grads:
        return {}
    n = world if world is not None else ctx.world_size
    return {
        "min_ranks": max(1, min(n, math.ceil(n * ctx.partial_min_fraction))),
        "grace_s": ctx.partial_grace_s,
    }


def grad_sync_opts(world: int | None = None) -> dict:
    """All gradient-sync kwargs this worker group was configured for —
    the partial K-of-N opts (``allow_partial_grads``) merged with the
    compression codec (``grad_compression``) — so train loops can write
    ``col.allreduce(grads, **train.grad_sync_opts())`` unconditionally
    and pick up both knobs. ``{}`` when neither is configured (the
    collective then runs its classic byte-identical path).

    With ``ScalingConfig(grad_overlap=True)`` the dict additionally
    carries ``overlap: True`` (plus ``bucket_bytes`` and
    ``error_feedback`` when configured). ``overlap`` is NOT an
    allreduce kwarg — it is the step loop's signal to switch to the
    bucketed async path::

        opts = train.grad_sync_opts()
        if opts.pop("overlap", False):
            pending = train.grad_bucketer().sync_async(grads)
            ...                      # remaining backward / other compute
            grads = train.grad_bucketer().unflatten(
                grads, pending.wait())   # join just before the update
        else:
            grads = col.allreduce(grads, **opts)
    """
    opts = partial_collective_opts(world)
    ctx = get_context()
    if ctx.grad_compression:
        opts["compression"] = ctx.grad_compression
    if ctx.grad_overlap:
        opts["overlap"] = True
        if ctx.grad_bucket_mb is not None:
            opts["bucket_bytes"] = int(ctx.grad_bucket_mb * (1 << 20))
        if ctx.grad_error_feedback:
            opts["error_feedback"] = True
    if ctx.zero_sharding:
        # Like "overlap", "zero" is the step loop's signal, not an
        # allreduce kwarg: pop it and switch to the sharded dataplane
        # (grad_bucketer().sync_sharded_async + zero_optimizer()).
        opts["zero"] = True
    return opts


def zero_optimizer(optimizer=None, params=None):
    """The cached :class:`~ray_tpu.train.zero.ZeroOptimizer` for this
    worker group's ZeRO-sharded weight update
    (``ScalingConfig(zero_sharding=True)``). The first call must pass
    ``optimizer=`` and ``params=`` (shard-local state is initialized
    from them, claiming ~1/world of the adamw bytes in the HBM
    ledger); later calls return the cache — and, when ``params`` is
    given and the context's (rank, world) moved under it (elastic
    reform inside one process), repartition deterministically, closing
    the stale shard's memory claim."""
    ctx = get_context()
    if not ctx.zero_sharding:
        raise RuntimeError(
            "zero sharding is off: start the trainer with "
            "ScalingConfig(zero_sharding=True)"
        )
    cached = ctx._zero_optimizer
    if cached is not None:
        if params is not None and (
            cached.world != ctx.world_size or cached.rank != ctx.rank
        ):
            cached.repartition(ctx.rank, ctx.world_size, params)
        return cached
    if optimizer is None or params is None:
        raise RuntimeError(
            "first zero_optimizer() call must pass optimizer= and "
            "params= to initialize the shard-local state"
        )
    from ray_tpu.train.zero import ZeroOptimizer

    ctx._zero_optimizer = ZeroOptimizer(
        optimizer, params, ctx.rank, ctx.world_size
    )
    return ctx._zero_optimizer


def grad_bucketer(group_name: str | None = None, world: int | None = None):
    """The configured :class:`~ray_tpu.collective.bucketer.GradBucketer`
    for this worker group's bucketed overlap sync — every
    ``ScalingConfig`` gradient-sync knob applied (bucket size, int8
    codec + error feedback, partial K-of-N, per-bucket algo
    selection). Cached on the context: the error-feedback residuals
    must persist across steps. ``group_name`` defaults to the
    trainer's collective group."""
    ctx = get_context()
    gname = group_name or ctx.collective_group
    if not gname:
        raise RuntimeError(
            "no collective group for the gradient bucketer: pass "
            "group_name= or start the trainer with "
            "ScalingConfig(distributed=True)"
        )
    cached = ctx._grad_bucketer
    if cached is not None and cached.group_name == gname:
        return cached
    from ray_tpu.collective.bucketer import GradBucketer

    popts = partial_collective_opts(world)
    ctx._grad_bucketer = GradBucketer(
        group_name=gname,
        bucket_bytes=(
            int(ctx.grad_bucket_mb * (1 << 20))
            if ctx.grad_bucket_mb is not None
            else None
        ),
        compression=ctx.grad_compression,
        min_ranks=popts.get("min_ranks"),
        grace_s=popts.get("grace_s"),
        error_feedback=ctx.grad_error_feedback,
    )
    return ctx._grad_bucketer


def slice_label() -> str | None:
    """This worker's node "slice" label (the fault domain it dies
    with), or None off-slice / when unresolved. Train loops use it to
    key slice-aware work (e.g. per-slice data shards) and the
    RAY_TPU_SLICE_FAIL chaos knob reads it to fail whole slices
    deterministically."""
    return get_context().slice_label


def note_partial_op(result) -> None:
    """Collective layer callback: a partial op skipped ranks under an
    active train session. The skipped fraction is charged to this step's
    ``degraded_frac`` (→ the head ledger's "degraded" category)."""
    ctx = _context
    if ctx is None:
        return
    ctx._partial_fracs.append(
        len(result.skipped) / max(1, result.world)
    )


def _own_node_notice() -> dict | None:
    """Drain notice for THIS worker's node (the one whose death this
    process will not survive), or None."""
    from ray_tpu.runtime import drain

    try:
        import ray_tpu.api as api

        core = getattr(api._runtime, "core", None)
        node_addr = getattr(core, "node_addr", None) if core else None
    # tpulint: allow(broad-except reason=drain-notice probe from a session that may have no runtime at all; None means no notice, which is the correct answer there)
    except Exception:  # noqa: BLE001 - session without a runtime
        node_addr = None
    return drain.for_node_addr(node_addr)


def preemption_notice() -> dict | None:
    """The active node-drain notice this train loop should react to, or
    None. Own-node notices win; otherwise ANY draining node's notice is
    returned so rank 0 can persist the emergency checkpoint for a peer
    whose node is about to die.

    The canonical loop pattern — checkpoint at the next step boundary
    inside the notice window, losing at most one step::

        ck = None
        if step % ckpt_every == 0 or train.preemption_notice():
            ck = save_my_state(...)
        train.report(metrics, checkpoint=ck)

    When this worker's OWN node is draining and a checkpoint was just
    handed to report(), report() raises :class:`PreemptedError` to
    unwind the attempt cleanly (toggle: RAY_TPU_TRAIN_EMERGENCY_
    CHECKPOINT)."""
    from ray_tpu.runtime import drain

    return _own_node_notice() or drain.any_notice()


def get_dataset_shard(name: str = "train"):
    """This worker's split of a dataset passed to JaxTrainer(datasets=...)
    (reference: ray.train.get_dataset_shard → DataIterator). Returns a
    ray_tpu.data Dataset over the shard's blocks; iterate with
    .iter_batches(batch_size=...).
    """
    ctx = get_context()
    refs = ctx.dataset_shards.get(name)
    if refs is None:
        raise KeyError(
            f"no dataset {name!r}; trainer got "
            f"{sorted(ctx.dataset_shards)}"
        )
    if isinstance(refs, dict) and "__token_dataset__" in refs:
        # Native token loader: re-open in this worker, sharded to rank.
        from ray_tpu.train.dataloader import TokenDataset

        return TokenDataset.from_descriptor(
            refs, rank=refs.get("rank", 0), world=refs.get("world", 1)
        )
    from ray_tpu.data.dataset import MaterializedDataset

    return MaterializedDataset(list(refs))


@contextlib.contextmanager
def step_span(
    flops: float | None = None,
    tokens: int | None = None,
    flops_per_token: float | None = None,
):
    """Wrap one training step for phase attribution, MFU, and goodput.

    ::

        with train.step_span(tokens=8192, flops_per_token=6 * n_params) as s:
            with s.phase("data_wait"):
                batch = next(it)
            with s.phase("compute"):
                state, m = train_step(state, batch)

    Phase durations feed the ``ray_tpu_train_step_phase_seconds``
    histogram and render as slices in ``ray_tpu timeline``; the step's
    FLOP count (``flops``, or ``tokens * flops_per_token``) yields
    per-step MFU. Phases named ``data_wait`` / ``checkpoint`` count as
    lost time in the head's per-job goodput. A no-op outside a train
    session or with RAY_TPU_TRAIN_TELEMETRY=0; a step that raises emits
    nothing (its time surfaces as restart loss, not productive time)."""
    ctx = _context
    from ray_tpu.train import telemetry

    if ctx is None or not telemetry.telemetry_enabled():
        yield telemetry.NOOP_STEP
        return
    if flops is None and tokens is not None and flops_per_token is not None:
        flops = tokens * flops_per_token
    timer = telemetry.StepTimer(flops)
    yield timer
    telemetry.finish_step(ctx, timer)


def report(metrics: dict, checkpoint: str | None = None) -> None:
    """Report metrics (all ranks) and optionally a checkpoint directory
    (rank 0's is persisted; reference: ray.train.report semantics)."""
    from ray_tpu.checkpoint.store import is_ckpt_uri

    ctx = get_context()
    ctx.latest_metrics = dict(metrics)
    entry: dict[str, Any] = {"metrics": dict(metrics)}
    ctx._last_checkpoint_s = 0.0
    if checkpoint is not None and is_ckpt_uri(checkpoint):
        # In-cluster shard-store checkpoint: nothing to copy — the async
        # persist runs in the background. The goodput ledger charges only
        # the snapshot stall the save() paid on this step loop.
        from ray_tpu.checkpoint import saver as _ckpt_saver

        entry["checkpoint"] = checkpoint
        ctx._last_checkpoint_s = _ckpt_saver.take_step_stall_seconds()
    elif checkpoint is not None and ctx.rank == 0:
        # Index continues from what's already persisted so a retry attempt
        # appends after the restored checkpoint instead of overwriting
        # earlier ones (which would make the newest-named dir stale).
        from ray_tpu.train.checkpoint import (
            checkpoint_dir_name,
            list_checkpoint_dirs,
        )

        run_dir = os.path.join(ctx.storage_path, ctx.experiment_name)
        os.makedirs(run_dir, exist_ok=True)
        existing = [i for i, _name in list_checkpoint_dirs(run_dir)]
        idx = max(existing, default=-1) + 1
        dest = os.path.join(run_dir, checkpoint_dir_name(idx))
        ckpt_t0 = time.perf_counter()
        if os.path.abspath(checkpoint) != os.path.abspath(dest):
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint, dest)
        ctx._last_checkpoint_s = time.perf_counter() - ckpt_t0
        entry["checkpoint"] = dest
    ctx.reports.append(entry)
    # Loops that never call train.step_span() still get goodput accounting:
    # each report() closes one implicit step (checkpoint copy included).
    from ray_tpu.train import telemetry

    now = time.time()
    if not ctx._used_step_timer and telemetry.telemetry_enabled():
        telemetry.implicit_step(ctx, now, metrics)
    ctx._last_report_wall = now
    # Emergency-checkpoint unwind: this worker's node is DRAINING and the
    # loop just put a checkpoint in hand — end the attempt NOW, at a step
    # boundary, so the controller resizes and resumes losing ≤1 step
    # instead of whatever remained of the inter-checkpoint interval.
    # Raised AFTER the step/checkpoint is fully accounted (ledger-wise
    # the step that produced the emergency checkpoint is productive).
    if checkpoint is not None:
        from ray_tpu._private import config

        if config.get("TRAIN_EMERGENCY_CHECKPOINT"):
            notice = _own_node_notice()
            if notice is not None:
                from ray_tpu.exceptions import PreemptedError

                if is_ckpt_uri(checkpoint):
                    # The snapshot is already offloaded; the drain window
                    # pays only the persist — barrier it so the attempt
                    # never unwinds on an uncommitted manifest.
                    from ray_tpu import checkpoint as _dist_ckpt

                    _dist_ckpt.wait_pending()
                raise PreemptedError(
                    node_id=notice.get("node_id"),
                    reason=notice.get("reason", ""),
                    deadline_ts=notice.get("deadline_ts"),
                )
