"""Training library: sharded train step (this module) and, above it, the
controller/worker-group `JaxTrainer` (ray_tpu.train.trainer), mirroring the
reference's Train v2 architecture (reference:
python/ray/train/v2/api/data_parallel_trainer.py:66)."""

from ray_tpu.train.step import (
    TrainState,
    make_optimizer,
    make_train_step,
    init_train_state,
    init_zero_train_state,
    jit_grad_step,
    state_logical_axes,
)
from ray_tpu.train import zero
from ray_tpu.train.zero import ZeroOptimizer
from ray_tpu.train.dataloader import TokenDataset
from ray_tpu.train.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from ray_tpu.exceptions import PreemptedError
from ray_tpu.train.session import (
    collective_group_name,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    grad_bucketer,
    grad_sync_opts,
    partial_collective_opts,
    preemption_notice,
    report,
    slice_label,
    step_span,
    zero_optimizer,
)
from ray_tpu.train.memory import MemoryPlan, plan as plan_memory
from ray_tpu.train.admission import AdmissionTicket, admit_gang
from ray_tpu.train.trainer import (
    ElasticScalingPolicy,
    FailureConfig,
    JaxTrainer,
    Result,
    RunConfig,
    ScalingConfig,
    ScalingPolicy,
)

__all__ = [
    "TokenDataset",
    "CheckpointManager",
    "restore_checkpoint",
    "save_checkpoint",
    "TrainState",
    "make_optimizer",
    "make_train_step",
    "init_train_state",
    "init_zero_train_state",
    "jit_grad_step",
    "state_logical_axes",
    "zero",
    "ZeroOptimizer",
    "zero_optimizer",
    "collective_group_name",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "grad_bucketer",
    "grad_sync_opts",
    "partial_collective_opts",
    "preemption_notice",
    "PreemptedError",
    "report",
    "slice_label",
    "step_span",
    "MemoryPlan",
    "plan_memory",
    "AdmissionTicket",
    "admit_gang",
    "ElasticScalingPolicy",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "ScalingPolicy",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu('train')
del _rlu
