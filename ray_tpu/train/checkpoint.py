"""Sharded checkpointing for train state (orbax-backed).

Reference: ray.train.Checkpoint is a directory handle on a pyarrow
filesystem (reference: python/ray/train/_checkpoint.py,
v2/_internal/execution/checkpoint/checkpoint_manager.py keeps top-K).
TPU-native difference: the payload is a pytree of sharded jax.Arrays —
orbax writes each host's shards and restores to any target sharding, so a
ZeRO-3 run checkpoints without gathering full params on one host.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import Any

import jax

logger = logging.getLogger("ray_tpu.train")

# One naming scheme for every checkpoint directory this library writes
# (CheckpointManager AND the report()-persisted dirs — they used to
# disagree: ckpt-* vs checkpoint_*, and discovery missed one or the
# other). Discovery still READS the legacy checkpoint_NNNNNN dirs so
# runs that predate the unification keep resuming.
CKPT_DIR_PREFIX = "ckpt-"
_LEGACY_PREFIX = "checkpoint_"


def checkpoint_dir_name(index: int) -> str:
    return f"{CKPT_DIR_PREFIX}{index:08d}"


def list_checkpoint_dirs(directory: str) -> list[tuple[int, str]]:
    """(index, name) for every checkpoint dir under ``directory`` —
    current and legacy naming — sorted by index. The single discovery
    helper both the trainer and the manager use."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    out = []
    for name in names:
        for prefix in (CKPT_DIR_PREFIX, _LEGACY_PREFIX):
            if name.startswith(prefix):
                try:
                    out.append((int(name[len(prefix):]), name))
                except ValueError:
                    pass
                break
    return sorted(out)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(path: str, state: Any, metadata: dict | None = None) -> str:
    """Write a pytree of (possibly sharded) arrays to `path`.

    Crash-safe: the write lands in a temp dir and is swapped in with a
    rename, so a preemption mid-save never destroys the previous copy.
    Multi-host note: every process must call this with the same `path`
    on shared storage (orbax coordinates the shard writes); only process
    0 performs the swap and metadata write, and callers should barrier
    before restoring.
    """
    path = os.path.abspath(path)
    # Deterministic suffixes: in multi-host mode every process must
    # target the SAME tmp dir for orbax's collective write.
    tmp = f"{path}.tmp"
    old = f"{path}.old"
    is_lead = jax.process_index() == 0
    if is_lead:
        # Lead-only: a non-lead recovering concurrently with the lead's
        # two-rename swap would resurrect the old dir mid-swap.
        _recover_interrupted_swap(path)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
    ckptr = _checkpointer()
    ckptr.save(os.path.join(tmp, "state"), state)
    ckptr.wait_until_finished()
    if not is_lead:
        return path
    if metadata is not None:
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(metadata, f)
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    shutil.rmtree(old, ignore_errors=True)
    return path


def _recover_interrupted_swap(path: str) -> None:
    """A crash between the two renames in save_checkpoint leaves the
    previous copy at `<path>.old` and nothing at `path`; put it back."""
    old = f"{path}.old"
    if not os.path.exists(old):
        return
    if os.path.exists(path):
        # Crash landed after the swap but before cleanup: drop the stale copy.
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.rename(old, path)


def restore_checkpoint(
    path: str, target: Any = None, shardings: Any = None
) -> Any:
    """Restore; `target` (a pytree of arrays or ShapeDtypeStructs) pins
    structure/dtypes, `shardings` (matching pytree of Shardings) places
    the restored arrays — pass the training mesh's shardings to resume a
    run on a different mesh layout than it was saved from."""
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    if jax.process_index() == 0:
        _recover_interrupted_swap(path)
    if jax.process_count() > 1:
        # Non-lead readers must not race the lead's recovery rename.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ray_tpu_ckpt_recover")
    state_path = os.path.join(path, "state")
    if target is None:
        return ckptr.restore(state_path)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
            target,
            shardings,
        )
    else:
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), target
        )
    return ckptr.restore(state_path, target=abstract)


def load_metadata(path: str) -> dict:
    meta = os.path.join(path, "metadata.json")
    if not os.path.exists(meta):
        return {}
    with open(meta) as f:
        return json.load(f)


class CheckpointManager:
    """Keep top-K checkpoints under a directory (reference:
    CheckpointManager checkpoint_manager.py — retention by
    checkpoint_score_attribute/order)."""

    def __init__(
        self,
        directory: str,
        *,
        num_to_keep: int = 2,
        score_attribute: str | None = None,
        score_order: str = "max",
        store_run: str | None = None,
    ):
        self.dir = os.path.abspath(directory)
        os.makedirs(self.dir, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # When set, restore_latest_valid also falls back to the
        # in-cluster replicated shard store (ray_tpu.checkpoint) under
        # this run name — a cluster without shared storage still resumes.
        self.store_run = store_run

    def _entries(self) -> list[tuple[int, str]]:
        # Recover any checkpoint whose save crashed mid-swap first, so
        # latest()/best() never silently skip it.
        for name in os.listdir(self.dir):
            if name.endswith(".old"):
                _recover_interrupted_swap(
                    os.path.join(self.dir, name[: -len(".old")])
                )
        return list_checkpoint_dirs(self.dir)

    def save(self, step: int, state: Any, metrics: dict | None = None) -> str:
        path = os.path.join(self.dir, checkpoint_dir_name(step))
        save_checkpoint(
            path, state, metadata={"step": step, "metrics": metrics or {}}
        )
        self._prune()
        return path

    def _score(self, name: str) -> float:
        meta = load_metadata(os.path.join(self.dir, name))
        val = meta.get("metrics", {}).get(self.score_attribute)
        if val is None:
            return float("-inf")
        return val if self.score_order == "max" else -val

    def _prune(self):
        entries = self._entries()
        if len(entries) <= self.num_to_keep:
            return
        if self.score_attribute is None:
            victims = entries[: len(entries) - self.num_to_keep]
        else:
            # Keep the best-scoring K, but never delete the latest (it is
            # the resume point).
            latest = entries[-1][1]
            ranked = sorted(
                (name for _, name in entries if name != latest),
                key=self._score,
                reverse=True,
            )
            keep = set(ranked[: self.num_to_keep - 1]) | {latest}
            victims = [(s, n) for s, n in entries if n not in keep]
        for _, name in victims:
            shutil.rmtree(os.path.join(self.dir, name), ignore_errors=True)

    def latest(self) -> str | None:
        entries = self._entries()
        return os.path.join(self.dir, entries[-1][1]) if entries else None

    def restore_latest_valid(
        self, target: Any = None, shardings: Any = None
    ) -> tuple[str, Any] | None:
        """Restore the newest checkpoint that actually loads.

        A partially-written or corrupt orbax dir (node died mid-save
        outside the rename window, disk hiccup) must cost one retention
        slot, not the whole run: on a restore failure, fall back to the
        next-older entry instead of failing the attempt. Returns
        ``(path, state)`` or None when nothing restores."""
        for _step, name in reversed(self._entries()):
            path = os.path.join(self.dir, name)
            try:
                return path, restore_checkpoint(
                    path, target=target, shardings=shardings
                )
            except Exception as e:  # noqa: BLE001 - any load failure
                logger.warning(
                    "checkpoint %s failed to restore (%r); falling back "
                    "to the previous one",
                    name,
                    e,
                )
        if self.store_run is not None:
            # No local dir restored (or none exist — e.g. no shared
            # filesystem): fall back to the in-cluster shard store.
            try:
                from ray_tpu import checkpoint as dist_ckpt

                step = dist_ckpt.latest_step(self.store_run)
                if step is not None:
                    state = dist_ckpt.restore(
                        self.store_run,
                        step,
                        target=target,
                        shardings=shardings,
                    )
                    return dist_ckpt.make_uri(self.store_run, step), state
            except Exception as e:  # noqa: BLE001 - store degraded:
                logger.warning(     # behave like no checkpoint found
                    "shard-store restore for run %r failed: %r",
                    self.store_run,
                    e,
                )
        return None

    def best(self) -> str | None:
        entries = self._entries()
        if not entries:
            return None
        if self.score_attribute is None:
            return os.path.join(self.dir, entries[-1][1])
        name = max((n for _, n in entries), key=self._score)
        return os.path.join(self.dir, name)
