"""Analytic train-step memory planner: predict per-config HBM bytes and
a fits/OOM verdict *before* committing a chip to the config.

BENCH_8B found the v5e fit boundary empirically — six llama configs
died in ResourceExhausted to learn that [4 layers, batch 2] fits. This
module is the closed-form version of that search: it prices every
resident and transient category of the fused train step
(train/step.py: forward scan+remat → chunked-CE → backward → adamw)
and compares against usable capacity, so capacity questions ("does
[6,1] fit?", "what does ZeRO sharding buy?") are answered in
microseconds instead of chip-hours. The planner's verdicts are
validated against BENCH_8B's empirical boundary (all seven configs) in
tier-1 and pinned in BENCH_8B.json's ``planner`` block.

Byte model (per chip, dp replicas shard only the batch, fsdp shards
params/optimizer/grads ZeRO-3 style, zero shards the optimizer state
across dp replicas — arXiv:2004.13336, train/zero.py):

- params: fp32 master weights (models/llama.py init_params), 4 B/param
- optimizer: adamw mu (``mu_dtype``, bf16 halves it) + fp32 nu
- grads: fp32, materialized tree-wide for clip_by_global_norm
- activations: remat="full" saves only the [B,S,d] residual stream per
  scanned layer (cfg.dtype) and re-materializes one layer's working
  set in backward — priced as ``ACT_WORKING_FACTOR`` × the layer's
  widest tensor [B,S,d_ff]; remat="none" keeps every intermediate
  (~the full working set per layer); "dots" sits between
- cross-entropy: chunked-CE peaks at one [B,chunk,V] fp32 logits block
  plus its gradient (train/step.py chunked_cross_entropy)
- collective scratch: the gradient bucketer's in-flight flat payloads
  (~2 size-targeted buckets in flight) plus int8 codec temporaries
  (wire ratio ~0.26 of the bucket) when compression is on

``XLA_RESERVE_BYTES`` holds back runtime workspace + fragmentation —
the compiler never hands user code the last half-GiB.
"""

from __future__ import annotations

import dataclasses

# Resident-state byte widths (see train/step.py make_optimizer and
# models/llama.py init_params).
PARAM_BYTES = 4  # fp32 master weights
NU_BYTES = 4     # adamw second moment stays fp32
GRAD_BYTES = 4   # fp32 grads (global-norm clip materializes the tree)

# Backward working-set multiplier for remat="full": gate/up activations,
# their grads, and the attention projections' recompute, in units of the
# layer's widest tensor [B, S, d_ff] at cfg.dtype. Calibrated against
# the BENCH_8B boundary ([4,2] fits with ~1.5 GiB predicted headroom;
# every listed OOM config over-subscribes).
ACT_WORKING_FACTOR = 6.0
# remat="none" keeps ~every intermediate of every layer instead of one
# layer's recompute window.
ACT_NONE_PER_LAYER_FACTOR = 8.0
# "dots" saves matmul outputs: between the two.
ACT_DOTS_PER_LAYER_FACTOR = 4.0

# XLA workspace + allocator fragmentation held back from "usable".
XLA_RESERVE_BYTES = 512 << 20

CE_CHUNK = 1024  # train/step.py chunked_cross_entropy default


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """One config's predicted per-chip memory bill and verdict."""

    n_layers: int
    batch: int
    seq: int
    n_params: int
    params_bytes: int
    optimizer_bytes: int
    grads_bytes: int
    activation_bytes: int
    ce_bytes: int
    scratch_bytes: int
    total_bytes: int
    capacity_bytes: int
    reserve_bytes: int
    usable_bytes: int
    headroom_bytes: int
    fits: bool

    @property
    def total_gb(self) -> float:
        return self.total_bytes / (1 << 30)

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["total_gb"] = round(self.total_gb, 2)
        out["headroom_gb"] = round(self.headroom_bytes / (1 << 30), 2)
        return out

    def breakdown(self) -> dict[str, int]:
        return {
            "params": self.params_bytes,
            "optimizer": self.optimizer_bytes,
            "grads": self.grads_bytes,
            "activations": self.activation_bytes,
            "cross_entropy": self.ce_bytes,
            "collective_scratch": self.scratch_bytes,
        }


def _dtype_bytes(dtype) -> int:
    """Width of a dtype given as a jnp dtype, numpy dtype, or name."""
    name = getattr(dtype, "__name__", None) or str(dtype)
    name = name.rsplit(".", 1)[-1]
    return {
        "bfloat16": 2, "float16": 2, "float32": 4, "float64": 8,
        "int8": 1, "float8_e4m3fn": 1, "float8_e5m2": 1,
    }.get(name, 4)


def default_capacity_bytes() -> int:
    """Detected device capacity (runtime/memory.py — chaos cap, backend
    limit, device-kind table), 16 GiB (v5e) when undetectable."""
    from ray_tpu.runtime import memory as rmem

    cap, _source = rmem.device_capacity_bytes()
    return cap if cap else 16 << 30


def plan(
    cfg,
    batch: int,
    seq: int,
    *,
    mu_dtype="bfloat16",
    hbm_gb: float | None = None,
    fsdp: int = 1,
    zero: int = 1,
    grad_bucket_mb: float | None = None,
    compression: str | None = None,
    reserve_bytes: int = XLA_RESERVE_BYTES,
) -> MemoryPlan:
    """Price one train-step config (a models.llama LlamaConfig plus
    batch/seq) against a chip's HBM and return the
    :class:`MemoryPlan` verdict. ``fsdp`` divides the resident state
    (params/optimizer/grads) ZeRO-3 style; ``zero`` divides the
    OPTIMIZER state only — the cross-replica weight-update sharding of
    arXiv:2004.13336 (train/zero.py): params stay full (the allgather
    rebuilds them) and grads still materialize tree-wide in backward,
    so only the adamw moments shrink. This lever is a measured claim:
    bench_zero.py pins the ledger's resident bytes against it.
    ``hbm_gb`` overrides capacity detection;
    ``grad_bucket_mb``/``compression`` price the bucketed-overlap
    scratch when the sync path uses it."""
    n_params = int(cfg.num_params())
    shard = max(1, int(fsdp))
    opt_shard = shard * max(1, int(zero))
    params_bytes = n_params * PARAM_BYTES // shard
    mu_bytes = n_params * _dtype_bytes(mu_dtype) // opt_shard
    optimizer_bytes = mu_bytes + n_params * NU_BYTES // opt_shard
    grads_bytes = n_params * GRAD_BYTES // shard
    act_dtype = _dtype_bytes(cfg.dtype)
    boundary = cfg.n_layers * batch * seq * cfg.d_model * act_dtype
    working_unit = batch * seq * cfg.d_ff * act_dtype
    remat = getattr(cfg, "remat", "full")
    if remat == "full":
        activation_bytes = boundary + int(
            ACT_WORKING_FACTOR * working_unit
        )
    elif remat == "dots":
        activation_bytes = boundary + int(
            ACT_DOTS_PER_LAYER_FACTOR * cfg.n_layers * working_unit
        )
    else:  # "none": every layer's working set stays live
        activation_bytes = boundary + int(
            ACT_NONE_PER_LAYER_FACTOR * cfg.n_layers * working_unit
        )
    chunk = min(CE_CHUNK, seq)
    # logits + their grad, fp32 (train/step.py chunked_cross_entropy)
    ce_bytes = 2 * batch * chunk * cfg.vocab_size * 4
    scratch_bytes = 0
    if grad_bucket_mb:
        bucket = int(grad_bucket_mb * (1 << 20))
        scratch_bytes = 2 * bucket  # ~2 buckets in flight
        if compression:
            scratch_bytes += int(0.26 * bucket)  # int8 wire + scales
    capacity_bytes = int(
        hbm_gb * (1 << 30) if hbm_gb else default_capacity_bytes()
    )
    usable = capacity_bytes - reserve_bytes
    total = (
        params_bytes + optimizer_bytes + grads_bytes
        + activation_bytes + ce_bytes + scratch_bytes
    )
    return MemoryPlan(
        n_layers=cfg.n_layers,
        batch=batch,
        seq=seq,
        n_params=n_params,
        params_bytes=params_bytes,
        optimizer_bytes=optimizer_bytes,
        grads_bytes=grads_bytes,
        activation_bytes=activation_bytes,
        ce_bytes=ce_bytes,
        scratch_bytes=scratch_bytes,
        total_bytes=total,
        capacity_bytes=capacity_bytes,
        reserve_bytes=reserve_bytes,
        usable_bytes=usable,
        headroom_bytes=usable - total,
        fits=total <= usable,
    )


def plan_bench8b(
    n_layers: int, batch: int, seq: int = 4096, hbm_gb: float = 16.0
) -> MemoryPlan:
    """The exact BENCH_8B recipe, priced: full-size llama3-8b layers,
    8k-row vocab shard, bf16 adamw mu, remat=full, seq 4096 (see
    bench_8b.py run())."""
    import dataclasses as _dc

    from ray_tpu.models import PRESETS

    cfg = _dc.replace(
        PRESETS["llama3_8b"],
        n_layers=n_layers,
        vocab_size=8192,
        attn_impl="flash",
        remat="full",
    )
    return plan(cfg, batch, seq, mu_dtype="bfloat16", hbm_gb=hbm_gb)
