"""Gang admission for the sweep engine: fits-in-HBM × healthy-chips.

The memory planner (train/memory.py) answers "does this config fit one
chip"; the head's slice/node tables answer "how many chips are actually
healthy right now". A trial gang is admitted only when both say yes —
admitting on raw capacity would place gangs onto draining nodes or
configs the first step would OOM, and the sweep would spend its makespan
on restart churn instead of trials.

Used by tune/sweep.py before every gang launch (and re-admission after
a preemption); usable standalone as ``train.admission.admit_gang``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

logger = logging.getLogger("ray_tpu.train")


@dataclass(frozen=True)
class AdmissionTicket:
    """One admission decision; ``admitted`` only when the gang both
    fits per-chip HBM and has enough healthy chips free."""

    admitted: bool
    reason: str
    required_chips: float
    free_chips: float
    total_chips: float
    plan: object | None = None  # MemoryPlan when a model spec was priced

    def __bool__(self) -> bool:
        return self.admitted


def cluster_chips(status: dict | None = None) -> tuple[float, float]:
    """(free, total) TPU chips on HEALTHY nodes: not draining, and not
    members of a slice that is itself draining/dead (a slice dies as a
    unit — its stray healthy hosts are condemned capacity). Falls back
    to CPU slots when the cluster reports no TPU resource at all, so
    the sweep engine packs correctly on CPU-only test rigs."""
    if status is None:
        status = _cluster_status()
    draining = set(status.get("draining") or {})
    sick_slices = {
        sid
        for sid, rec in (status.get("slices") or {}).items()
        if rec.get("state") != "healthy"
        or any(nid in draining for nid in rec.get("nodes") or ())
    }
    node_slice = {
        nid: sid
        for sid, rec in (status.get("slices") or {}).items()
        for nid in rec.get("nodes") or ()
    }
    nodes = status.get("nodes") or {}
    kind = "TPU" if any(
        (n.get("resources") or {}).get("TPU") for n in nodes.values()
    ) else "CPU"
    free = total = 0.0
    for nid, n in nodes.items():
        if nid in draining or node_slice.get(nid) in sick_slices:
            continue
        total += float((n.get("resources") or {}).get(kind, 0.0))
        free += float((n.get("available") or {}).get(kind, 0.0))
    return free, total


def _cluster_status() -> dict:
    import ray_tpu

    rt = ray_tpu.api._runtime
    return rt.run(rt.core.head.call("cluster_status"))


def admit_gang(
    num_workers: int,
    chips_per_worker: float = 1.0,
    *,
    plan_kwargs: dict | None = None,
    headroom_fraction: float | None = None,
    status: dict | None = None,
) -> AdmissionTicket:
    """Admission check for one trial gang.

    ``plan_kwargs`` (optional) prices the config through
    ``train.plan_memory``: ``{"cfg": <LlamaConfig>, "batch": ...,
    "seq": ..., **plan-kwargs}``. ``headroom_fraction`` (default knob
    ``TUNE_ADMISSION_HEADROOM``) additionally requires that fraction of
    usable HBM left free — a sweep packing many gangs wants margin the
    single-job planner doesn't."""
    from ray_tpu._private import config as _config

    plan = None
    if plan_kwargs:
        from ray_tpu.train.memory import plan as plan_memory

        kw = dict(plan_kwargs)
        plan = plan_memory(
            kw.pop("cfg"), kw.pop("batch"), kw.pop("seq"), **kw
        )
        if headroom_fraction is None:
            headroom_fraction = _config.get("TUNE_ADMISSION_HEADROOM")
        need_free = headroom_fraction * plan.usable_bytes
        if not plan.fits or plan.headroom_bytes < need_free:
            return AdmissionTicket(
                admitted=False,
                reason=(
                    f"memory plan rejects config: total "
                    f"{plan.total_gb:.2f} GiB vs usable "
                    f"{plan.usable_bytes / (1 << 30):.2f} GiB "
                    f"(headroom floor {headroom_fraction:.0%})"
                ),
                required_chips=num_workers * chips_per_worker,
                free_chips=0.0,
                total_chips=0.0,
                plan=plan,
            )
    free, total = cluster_chips(status)
    required = num_workers * max(0.0, chips_per_worker)
    if required > free:
        return AdmissionTicket(
            admitted=False,
            reason=(
                f"gang needs {required:g} healthy chips, "
                f"{free:g}/{total:g} free"
            ),
            required_chips=required,
            free_chips=free,
            total_chips=total,
            plan=plan,
        )
    return AdmissionTicket(
        admitted=True,
        reason="fits",
        required_chips=required,
        free_chips=free,
        total_chips=total,
        plan=plan,
    )
