"""ZeRO-style cross-replica sharded optimizer (arXiv:2004.13336).

Every dp replica holding full fp32 optimizer state is the capacity
wall BENCH_8B measured (params+adamw ≈ 9.4 GB of a 16 GB v5e). This
module shards the *weight update* across replicas instead: leaf
ownership is round-robin over the sorted leaf keys — the EXACT
partition ``checkpoint/manifest.py owned_items`` uses — so each rank
keeps optimizer state for ~1/world of the leaves, applies the update
only to those, and the sharded state it checkpoints is the state it
already holds (no gather on save, no full materialization on restore).

The dataplane half lives in ``collective/bucketer.py``
(:meth:`GradBucketer.sync_sharded_async`): reduce-scatter delivers each
owner its reduced gradients, the shard-local update runs here, and the
weight all-gather rebuilds full params on every rank.

The optimizer is applied PER LEAF, so cross-leaf transforms (optax's
``clip_by_global_norm``) would silently become per-leaf clips — pass an
uncoupled optimizer (plain adamw) and, when clipping is needed, price
the true global norm with :func:`global_grad_norm` (one scalar
allreduce) and pre-scale the gradients.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ray_tpu.checkpoint import manifest as _manifest

#: Key prefix of the sharded optimizer subtree in a checkpoint state —
#: leaves under it are persisted by WHOEVER HOLDS THEM (they are a
#: disjoint shard by construction), not round-robin re-partitioned.
CKPT_PREFIX = "['zero_opt']"


def partition(keys, world: int) -> dict[str, int]:
    """Round-robin leaf ownership over SORTED keys: key i belongs to
    rank ``i % world``. Deterministic in (keys, world) — a resize
    re-partitions identically on every worker."""
    return {k: i % max(1, int(world)) for i, k in enumerate(sorted(keys))}


def global_grad_norm(owned_sq_sum: float, group_name: str | None = None):
    """True global gradient norm from this rank's owned-leaf square
    sum: one scalar allreduce over the group (each leaf is owned by
    exactly one rank, so the sum is exact). Without a group (world 1 /
    tests) the local sum is the global one."""
    total = float(owned_sq_sum)
    if group_name:
        import ray_tpu.collective as col

        total = float(
            np.asarray(
                col.allreduce(
                    np.asarray(total, np.float64), group_name=group_name
                )
            )
        )
    return float(np.sqrt(total))


class ZeroOptimizer:
    """Shard-local optimizer state for one dp rank.

    ::

        zo = zero.ZeroOptimizer(optax.adamw(1e-3), params, rank, world)
        pending = bucketer.sync_sharded_async(grads)
        updated = zo.apply(pending.wait(), params)     # owned leaves
        params = bucketer.zero_unflatten(
            params, pending.allgather_updated(updated).wait())

    The resident optimizer footprint is claimed in the device-memory
    ledger under ``train.state.optimizer`` (the same tag the replicated
    path uses), priced at the SHARD's bytes — the HBM ledger then
    attributes the ~1/world footprint honestly, and a repartition
    closes the stale claim before registering the new one (TPU404's
    no-leaked-Registration discipline)."""

    def __init__(
        self,
        optimizer,
        params,
        rank: int,
        world: int,
        mem_tag: str = "train.state.optimizer",
    ):
        self.optimizer = optimizer
        self.mem_tag = mem_tag
        self._mem_reg = None
        self.rank = 0
        self.world = 1
        self.keys: list[str] = []
        self.owners: dict[str, int] = {}
        #: leaf key → optax state for the leaves THIS rank owns
        self.states: dict[str, Any] = {}
        self.repartition(rank, world, params)

    # ------------------------------------------------------- partition
    def owned_keys(self) -> list[str]:
        return [k for k in self.keys if self.owners[k] == self.rank]

    def leaf_map(self, tree) -> dict[str, Any]:
        """{key: leaf} of a params-shaped tree (manifest key order)."""
        return dict(_manifest.flatten_with_keys(tree))

    def repartition(self, rank: int, world: int, params) -> None:
        """Re-own after a world change (elastic resize): recompute the
        round-robin partition, keep states for still-owned leaves, init
        fresh states for newly-owned ones, drop the rest, and replace
        the memory claim (the stale shard's Registration is closed, not
        leaked)."""
        self.rank = int(rank)
        self.world = max(1, int(world))
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"rank {rank} out of range for world {world}"
            )
        leaves = self.leaf_map(params)
        self.keys = list(leaves)
        self.owners = partition(self.keys, self.world)
        fresh: dict[str, Any] = {}
        for key in self.owned_keys():
            prev = self.states.get(key)
            fresh[key] = (
                prev if prev is not None
                else self.optimizer.init(leaves[key])
            )
        self.states = fresh
        self._register_memory()

    # ---------------------------------------------------------- update
    def apply(
        self,
        owned_grads: dict[str, Any],
        params,
        grad_scale: float | None = None,
        update_fn: Callable | None = None,
    ) -> dict[str, Any]:
        """Shard-local weight update: for every owned leaf, apply the
        optimizer to its reduced gradient and return ``{key: updated
        param}`` — the input of
        :meth:`~ray_tpu.collective.bucketer.PendingZeroSync.allgather_updated`.
        ``grad_scale`` pre-multiplies gradients (1/world for a mean
        over a SUM-reduced sync, or a global-norm clip factor);
        ``update_fn(key, grad, state, param) -> (new_param, new_state)``
        overrides the optax application (hand-rolled deterministic
        updates in the parity twin)."""
        import optax

        leaves = self.leaf_map(params)
        out: dict[str, Any] = {}
        for key in self.owned_keys():
            if key not in owned_grads:
                raise KeyError(
                    f"sharded sync delivered no gradient for owned "
                    f"leaf {key}; got {sorted(owned_grads)[:4]}…"
                )
            grad = owned_grads[key]
            if grad_scale is not None:
                grad = np.asarray(grad) * grad_scale
            if update_fn is not None:
                out[key], self.states[key] = update_fn(
                    key, grad, self.states[key], leaves[key]
                )
                continue
            updates, self.states[key] = self.optimizer.update(
                grad, self.states[key], leaves[key]
            )
            out[key] = optax.apply_updates(leaves[key], updates)
        return out

    # ------------------------------------------------------ checkpoint
    def checkpoint_tree(self) -> dict:
        """The sharded-state subtree to merge into the checkpointed
        state: ``{"zero_opt": {leaf key: optax state}}`` holding ONLY
        this rank's shard. Pass ``local_prefixes=(zero.CKPT_PREFIX,)``
        to the saver so these leaves persist as-held instead of being
        round-robin re-partitioned."""
        return {"zero_opt": dict(self.states)}

    def restore_target(self, params) -> dict:
        """A freshly-initialized checkpoint subtree for the leaves this
        rank NOW owns — the ``target=`` for a resharded restore (M ≠ N
        workers): each new owner pulls exactly its shard's chunks from
        whichever replicas survive."""
        leaves = self.leaf_map(params)
        return {
            "zero_opt": {
                key: self.optimizer.init(leaves[key])
                for key in self.owned_keys()
            }
        }

    def load_checkpoint_tree(self, tree: dict) -> None:
        """Adopt restored optimizer states (the ``zero_opt`` subtree of
        a :meth:`restore_target`-shaped restore)."""
        states = tree.get("zero_opt", tree)
        for key in self.owned_keys():
            if key in states:
                self.states[key] = states[key]
        self._register_memory()

    # ---------------------------------------------------------- memory
    def shard_bytes(self) -> int:
        import jax

        return int(
            sum(
                leaf.nbytes
                for state in self.states.values()
                for leaf in jax.tree_util.tree_leaves(state)
                if hasattr(leaf, "nbytes")
            )
        )

    def _register_memory(self) -> None:
        from ray_tpu.runtime import memory as rmem

        if self._mem_reg is not None:
            self._mem_reg.close()
            self._mem_reg = None
        if not rmem.enabled():
            return
        self._mem_reg = rmem.track(
            self.mem_tag, kind="optimizer", nbytes=self.shard_bytes()
        )
        rmem.tag_arrays(self.mem_tag, "optimizer", list(self.states.values()))

    def close(self) -> None:
        if self._mem_reg is not None:
            self._mem_reg.close()
            self._mem_reg = None
