"""Sharded next-token-prediction train step for the flagship model.

One pjit'd program: forward (scan+remat) → cross-entropy → backward → adamw
update. Under a mesh with fsdp>1 the optimizer state and params are sharded
(ZeRO-3); XLA inserts the param all-gathers and gradient reduce-scatters.
The reference reaches the same endpoint via torch DDP/FSDP process groups
(reference: python/ray/train/torch/config.py:73); here it is one compiled
XLA program per (mesh, shapes).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_logical_axes,
)
from ray_tpu.parallel.sharding import is_axes_leaf, tree_shardings, use_mesh


def _model_fns(cfg: LlamaConfig):
    """(init, logical_axes) for the config's model family — dense Llama
    or MoE (ray_tpu.models.moe adds expert-parallel params)."""
    from ray_tpu.models.moe import (
        MoEConfig,
        init_moe_params,
        moe_param_logical_axes,
    )

    if isinstance(cfg, MoEConfig):
        return init_moe_params, moe_param_logical_axes
    return init_params, param_logical_axes


class TrainState(NamedTuple):
    step: jnp.ndarray  # scalar int32
    params: Any
    opt_state: Any


def make_optimizer(
    lr: float = 3e-4,
    warmup: int = 100,
    total_steps: int = 10000,
    weight_decay: float = 0.1,
    grad_clip: float = 1.0,
    mu_dtype=None,
) -> optax.GradientTransformation:
    """``mu_dtype=jnp.bfloat16`` halves the first-moment memory (the
    8-bit-optimizer-style tradeoff; the variance stays fp32) — measured
    loss-neutral on the bench model and frees HBM for batch at 8B."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), lr * 0.1
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(
            sched, b1=0.9, b2=0.95, weight_decay=weight_decay,
            mu_dtype=mu_dtype,
        ),
    )


def init_train_state(
    key: jax.Array, cfg: LlamaConfig, optimizer: optax.GradientTransformation
) -> TrainState:
    init, _ = _model_fns(cfg)
    params = init(key, cfg)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
    )
    _register_state_memory(state)
    return state


def init_zero_train_state(
    key: jax.Array,
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    rank: int,
    world: int,
):
    """ZeRO-sharded counterpart of :func:`init_train_state`: full
    params plus a :class:`~ray_tpu.train.zero.ZeroOptimizer` holding
    optimizer state for this rank's ~1/world of the leaves only
    (arXiv:2004.13336). Both tenants are claimed in the device-memory
    ledger — params at full size, the optimizer at SHARD size — so the
    HBM ledger and OOM forensics price the ZeRO win honestly instead
    of assuming replicated adamw. Returns ``(params, zero_optimizer)``;
    the step loop syncs grads with
    ``GradBucketer.sync_sharded_async`` and applies
    ``zero_optimizer.apply`` between the two hops."""
    from ray_tpu.train.zero import ZeroOptimizer

    init, _ = _model_fns(cfg)
    params = init(key, cfg)
    _register_tagged(
        "train.state.params", "params", params
    )
    zo = ZeroOptimizer(optimizer, params, rank, world)
    return params, zo


def jit_grad_step(cfg: LlamaConfig, attn_fn=None):
    """jit the forward+backward half of the train step:
    ``(params, batch) -> (metrics, grads)``. For dataplanes that sync
    and update OUTSIDE the compiled program — the ZeRO-sharded path
    reduce-scatters these grads, updates shard-locally, and allgathers
    weights — so the optimizer math never has to live inside the fused
    step."""

    def grad_step(params, batch):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, metrics), grads = grad_fn(params, batch, cfg, attn_fn)
        return metrics, grads

    return jax.jit(grad_step)


# Live memory-ledger claims for the resident train state, keyed by
# tag. Retained so re-initialization (elastic resize, new attempt)
# explicitly retires the previous claim instead of leaning on
# tag-replacement (TPU404), and so teardown CAN close them.
_STATE_REGS: dict[str, object] = {}


def _tree_bytes(tree) -> int:
    return int(
        sum(
            leaf.nbytes
            for leaf in jax.tree_util.tree_leaves(tree)
            if hasattr(leaf, "nbytes")
        )
    )


def _register_tagged(tag: str, kind: str, tree) -> None:
    """One resident-state ledger claim with the _STATE_REGS discipline:
    the previous claim under the tag is explicitly retired (elastic
    resize / new attempt re-inits must not leak a Registration), and
    the arrays are tagged for OOM forensics."""
    from ray_tpu.runtime import memory as rmem

    if not rmem.enabled():
        return
    old = _STATE_REGS.get(tag)
    if old is not None:
        old.close()
    _STATE_REGS[tag] = rmem.track(tag, kind=kind, nbytes=_tree_bytes(tree))
    rmem.tag_arrays(tag, kind, tree)


def _register_state_memory(state: TrainState) -> None:
    """Claim the resident train state in the device-memory ledger
    (runtime/memory.py): params and optimizer moments are the two
    biggest fixed tenants of HBM (BENCH_8B: ~9.4 GB of a 16 GB v5e at
    4 full llama3-8b layers), so they register at creation — and their
    arrays are tagged so an OOM forensics report names them."""
    from ray_tpu.runtime import memory as rmem

    if not rmem.enabled():
        return
    _register_tagged("train.state.params", "params", state.params)
    _register_tagged("train.state.optimizer", "optimizer", state.opt_state)


class _Box:
    """Opaque wrapper so an axes tuple traverses pytree maps as one leaf."""

    __slots__ = ("axes",)

    def __init__(self, axes):
        self.axes = axes


def state_logical_axes(
    cfg: LlamaConfig, optimizer: optax.GradientTransformation
) -> TrainState:
    """Logical axes for every leaf of TrainState (opt state mirrors params).

    `optax.tree_map_params` pairs each param-shaped leaf of the optimizer
    state with its parameter by *position in the tree*, so adam moments get
    exactly their parameter's axes (shape coincidences like wq [L,d,hq] vs
    wo [L,hq,d] with hq==d cannot cross-contaminate); non-param leaves
    (e.g. adam's count) get ()."""
    init, logical_axes = _model_fns(cfg)
    p_axes = logical_axes(cfg)
    p_shapes = jax.eval_shape(partial(init, cfg=cfg), jax.random.key(0))
    opt_shapes = jax.eval_shape(optimizer.init, p_shapes)

    boxed = jax.tree.map(_Box, p_axes, is_leaf=is_axes_leaf)
    axes_state = optax.tree_map_params(
        optimizer,
        lambda _, box: box.axes,
        opt_shapes,
        boxed,
        transform_non_params=lambda _: (),
    )

    return TrainState(step=(), params=p_axes, opt_state=axes_state)


def chunked_cross_entropy(
    hidden: jnp.ndarray,  # [B, S, d] final-norm hidden states
    lm_head: jnp.ndarray,  # [d, V]
    targets: jnp.ndarray,  # [B, S] int32
    dtype,
    # 1024 measured fastest on v5e at B8/S2048/V32k (+0.9% step over
    # 512: fewer scan trips at the same peak-logits memory order).
    chunk: int = 1024,
) -> jnp.ndarray:
    """Mean next-token CE without materializing [B, S, V] logits.

    A rematerialized scan projects one sequence-chunk of hidden states at
    a time, so peak memory is O(B·chunk·V) instead of O(B·S·V) — at
    32k vocab this is what bounds the trainable batch size on a chip.
    """
    b, s, d = hidden.shape
    if s % chunk:
        # Largest divisor <= chunk: falling back to chunk=s would
        # materialize the full [B, S, V] logits for any length the
        # default doesn't divide (e.g. seq 2560) — a multi-GB memory
        # cliff. Divisor-poor lengths (primes) floor at 128: below
        # that the scan degrades to matvecs, and a single full-logits
        # pass is the lesser evil for such (rare, short-eval) shapes.
        chunk = next(c for c in range(min(chunk, s), 0, -1) if s % c == 0)
        if chunk < 128:
            chunk = s
    n = s // chunk
    xc = hidden.reshape(b, n, chunk, d).swapaxes(0, 1)  # [n, B, chunk, d]
    tc = targets.reshape(b, n, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def body(acc, xt):
        xcb, tcb = xt
        logits = (xcb @ lm_head.astype(dtype)).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, tcb[..., None], axis=-1)[..., 0]
        return acc + (logz - tgt).sum(), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (xc, tc))
    return total / (b * s)


def loss_fn(
    params: Any,
    batch: dict[str, jnp.ndarray],
    cfg: LlamaConfig,
    attn_fn=None,
) -> tuple[jnp.ndarray, dict[str, jnp.ndarray]]:
    """Next-token cross entropy. batch["tokens"]: [B, S+1] int32."""
    from ray_tpu.models.llama import forward_with_aux
    from ray_tpu.models.moe import MoEConfig, moe_forward

    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if isinstance(cfg, MoEConfig):
        hidden, aux = moe_forward(
            params, inputs, cfg, attn_fn=attn_fn, return_hidden=True
        )
    else:
        hidden, aux = forward_with_aux(
            params, inputs, cfg, attn_fn=attn_fn, return_hidden=True
        )
        aux = None
    ce = chunked_cross_entropy(
        hidden, params["lm_head"], targets, cfg.dtype
    )
    metrics = {"loss": ce, "perplexity": jnp.exp(ce)}
    if aux is None:
        return ce, metrics
    metrics["aux_loss"] = aux
    return ce + aux, metrics


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    attn_fn=None,
):
    """Returns train_step(state, batch) -> (state, metrics), ready to jit."""

    def train_step(state: TrainState, batch: dict[str, jnp.ndarray]):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, metrics), grads = grad_fn(state.params, batch, cfg, attn_fn)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(state.step + 1, params, opt_state), metrics

    return train_step


def jit_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh,
    batch_axes: tuple = ("batch", None),
):
    """jit the train step with sharded state in/out and donated state.

    ``batch_axes`` shards the raw token batch [B, S+1]; the sequence dim is
    left unsharded by default (S+1 rarely divides sp) — activations get
    their seq sharding from the `constrain` calls inside the model.
    """
    attn_fn = None
    if cfg.attn_impl == "ring":
        from ray_tpu.parallel.ring_attention import make_ring_attention

        attn_fn = make_ring_attention(mesh)
    elif cfg.attn_impl == "ulysses":
        from ray_tpu.parallel.ulysses import make_ulysses_attention

        attn_fn = make_ulysses_attention(mesh)
    elif cfg.attn_impl == "flash":
        from ray_tpu.ops.pallas.flash_attention import make_flash_attention

        attn_fn = make_flash_attention(mesh)
    elif cfg.attn_impl != "dense":
        raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")
    step = make_train_step(cfg, optimizer, attn_fn=attn_fn)

    if mesh is None or mesh.size == 1:
        # Single chip: sharding annotations + the mesh context are pure
        # overhead — the constraint ops inhibit fusion (measured ~1% on
        # the v5e bench) — and computing the shardings at all would
        # crash for mesh=None. Plain donated jit.
        return jax.jit(step, donate_argnums=(0,))

    axes = state_logical_axes(cfg, optimizer)
    state_sh = tree_shardings(mesh, axes)
    batch_sh = {"tokens": tree_shardings(mesh, batch_axes)}

    def step_in_mesh(state, batch):
        with use_mesh(mesh):
            return step(state, batch)

    return jax.jit(
        step_in_mesh,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, None),
        donate_argnums=(0,),
    )
