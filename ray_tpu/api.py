"""Public core API: init / remote / get / put / wait / actors.

Mirrors the reference's Python surface (reference:
python/ray/_private/worker.py `init` :1412, `get` :2846, `put` :3015;
python/ray/remote_function.py:314 `_remote`; python/ray/actor.py) over the
ray_tpu runtime. All public calls are synchronous wrappers around the
runtime's asyncio loop, which runs on a background thread in the driver
and on the main thread in workers.
"""

from __future__ import annotations

import asyncio
import atexit
import functools
import os
import threading
from typing import Any, Sequence

from ray_tpu._private.ids import JobID
from ray_tpu.exceptions import RayTpuError
from ray_tpu.runtime.core_worker import ActorSubmitTarget, CoreWorker

_DEFAULT_TIMEOUT = None


class _Runtime:
    def __init__(self):
        self.loop: asyncio.AbstractEventLoop | None = None
        self.thread: threading.Thread | None = None
        self.head = None
        self.node = None
        self.core: CoreWorker | None = None
        self.mode: str | None = None
        self.session: str | None = None

    @property
    def ready(self) -> bool:
        return self.core is not None

    def run(self, coro, timeout=None):
        if self.loop is None:
            raise RayTpuError("ray_tpu.init() has not been called")
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout
        )


_runtime = _Runtime()


def is_initialized() -> bool:
    return _runtime.ready


def _print_worker_log(msg: dict) -> None:
    """Render a worker-log pubsub record on the driver's stdout with the
    reference's "(prefix pid=N, node)" framing."""
    import sys as _sys

    data = msg.get("data", "")
    prefix = (
        f"({msg.get('worker_id', '?')[:8]} pid={msg.get('pid')}, "
        f"node={str(msg.get('node_id', '?'))[:8]})"
    )
    out = "".join(
        f"{prefix} {line}\n" for line in data.splitlines() if line.strip()
    )
    if out:
        _sys.stdout.write(out)
        _sys.stdout.flush()


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    resources: dict | None = None,
    object_store_dir: str | None = None,
    observer: bool = False,
    labels: dict | None = None,
    _system_config: dict | None = None,
) -> dict:
    """Start (or connect to) a cluster and attach this process as driver.

    With no ``address``, starts an in-process head service plus a node
    manager for this host (reference: ray.init head path, worker.py:1412 →
    node.py start_head_processes :1316). ``address="ray://host:port"``
    attaches as a REMOTE CLIENT driver (reference: Ray Client,
    python/ray/util/client/): no local node joins the cluster — leases go
    through the head and large puts upload to a cluster node.
    """
    if _runtime.ready:
        raise RayTpuError("ray_tpu is already initialized")
    if _system_config:
        # Typed overrides of the config registry (reference:
        # ray.init(_system_config=...) threaded through the GCS); the
        # env export makes spawned workers inherit them.
        from ray_tpu._private import config as _config

        _config.set_system_config(_system_config)
    if address is None:
        # Job drivers launched by the job manager inherit the cluster
        # address (reference: RAY_ADDRESS env for `ray job submit`
        # entrypoints).
        from ray_tpu._private import config as _config

        address = _config.get("ADDRESS") or None
    client = False
    if address is not None and address.startswith("ray://"):
        client = True
        address = address[len("ray://"):]
    if observer and address is None:
        # Validate before the loop thread / head service start so a bad
        # call leaks nothing.
        raise RayTpuError("observer=True requires address=")
    if client and not address:
        raise RayTpuError("client mode requires ray://host:port")

    loop = asyncio.new_event_loop()
    thread = threading.Thread(
        target=loop.run_forever, name="ray_tpu_runtime", daemon=True
    )
    thread.start()
    _runtime.loop = loop
    _runtime.thread = thread

    async def _bootstrap():
        from ray_tpu.runtime.head import HeadService
        from ray_tpu.runtime.node import NodeManager, detect_resources
        from ray_tpu.runtime.object_store import default_store_dir

        session = JobID.random().hex()[:12]
        if address is None:
            # Library-embedded heads journal only when HEAD_JOURNAL is
            # set: the ephemeral session store dir is rmtree'd at
            # shutdown, so a journal there would cost a write per
            # mutation and never be replayable. CLI/daemon heads (whose
            # session dir persists) journal by default (daemon.py).
            head = HeadService()
            head_addr = await head.start()
        else:
            head = None
            head_addr = address

        if client:
            # Client drivers keep a PRIVATE store dir (pull cache): the
            # cluster's stores live on its nodes.
            import tempfile

            store_dir = object_store_dir or os.path.join(
                tempfile.gettempdir(), f"ray_tpu-client-{session}"
            )
        else:
            store_dir = object_store_dir or default_store_dir(session)
        if observer or client:
            # Read-only connection (CLI/dashboard) or remote client: no
            # schedulable node, no worker pool — the cluster must not
            # see this process as capacity (reference: `ray status`
            # attaches without adding a raylet; Ray Client drivers).
            node = None
        else:
            total = detect_resources()
            if num_cpus is not None:
                total["CPU"] = float(num_cpus)
            total.update(resources or {})
            node = NodeManager(
                head_addr, store_dir, resources=total, labels=labels
            )
            await node.start()

        core = CoreWorker(
            mode="client" if client else "driver",
            head_addr=head_addr,
            node_addr=node.addr if node else "",
            store_dir=store_dir,
        )
        await core.start()
        if not observer:
            from ray_tpu._private import config as _config

            if _config.get("LOG_TO_DRIVER"):
                # Stream worker stdout/stderr to this driver (reference:
                # print_worker_logs worker.py:2295 — the log monitor
                # publishes, every driver prints).
                await core.subscribe("logs", _print_worker_log)
        return head, node, core, session, head_addr

    head, node, core, session, head_addr = _runtime.run(_bootstrap())
    _runtime.head = head
    _runtime.node = node
    _runtime.core = core
    _runtime.mode = "client" if client else "driver"
    _runtime.session = session
    atexit.register(shutdown)
    # tpulint: allow(TPU703 reason=opt-in telemetry gate is deliberately env-only — unset means provably nothing leaves the machine, no config layer can flip it)
    if os.environ.get("RAY_TPU_USAGE_REPORT_URL"):
        # Opt-in usage POST (reference: usage_lib report on init) —
        # fire-and-forget off-thread, never on the init path.
        from ray_tpu._private import usage

        threading.Thread(
            target=usage.report_if_enabled, daemon=True
        ).start()
    return {
        "address": head_addr,
        "session": session,
        "node_id": node.node_id if node else None,
    }


def shutdown() -> None:
    if not _runtime.ready:
        return

    async def _teardown():
        await _runtime.core.stop()
        if _runtime.node is not None:
            await _runtime.node.stop()
        if _runtime.head is not None:
            await _runtime.head.stop()

    try:
        _runtime.run(_teardown(), timeout=10)
    # tpulint: allow(broad-except reason=shutdown is best-effort by contract; a half-dead runtime loop must not prevent the store destroy and process exit below)
    except Exception:  # noqa: BLE001
        pass
    if _runtime.mode in ("driver", "client"):
        # Driver (observer, client) sessions own their store dir; worker
        # processes share their node's and must not delete it.
        _runtime.core.store.destroy()
    def _drain_and_stop():
        # Cancel stragglers (serve demand reporters, pollers), then stop
        # only after their CancelledErrors have actually been delivered
        # (gather resolves post-delivery) — stopping in the same
        # iteration would leave them pending and still emit "Task was
        # destroyed but it is pending!" at interpreter exit.
        stragglers = list(asyncio.all_tasks(_runtime.loop))
        for task in stragglers:
            task.cancel()

        async def _finish():
            await asyncio.gather(*stragglers, return_exceptions=True)
            _runtime.loop.stop()

        asyncio.ensure_future(_finish())
        # Bounded drain: a straggler that absorbs cancellation must not
        # hold the loop (and the join below) hostage.
        _runtime.loop.call_later(3.0, _runtime.loop.stop)

    _runtime.loop.call_soon_threadsafe(_drain_and_stop)
    _runtime.thread.join(timeout=5)
    _runtime.__init__()


def _attach_worker(core: CoreWorker, loop: asyncio.AbstractEventLoop):
    """Called by worker_main so tasks can use the public API re-entrantly."""
    _runtime.loop = loop
    _runtime.core = core
    _runtime.mode = "worker"


# ----------------------------------------------------------------- refs
class ObjectRef:
    """A reference to a (possibly pending) object; carries its owner's
    address so any holder can resolve it (ownership model, SURVEY.md §5)."""

    __slots__ = ("hex", "owner_addr")

    def __init__(self, hex_id: str, owner_addr: str | None):
        self.hex = hex_id
        self.owner_addr = owner_addr

    def __reduce__(self):
        return (ObjectRef, (self.hex, self.owner_addr))

    def __hash__(self):
        return hash(self.hex)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.hex == self.hex

    def __repr__(self):
        return f"ObjectRef({self.hex[:12]}…@{self.owner_addr})"


# ----------------------------------------------------------- task verbs
def put(value: Any) -> ObjectRef:
    return _runtime.run(_runtime.core.put(value))


def broadcast(
    ref: "ObjectRef",
    timeout: float | None = None,
    strict: bool = True,
    return_details: bool = False,
):
    """Relay-broadcast a store-resident object into every node's store
    (reference: put-then-fan-out rides push_manager.h:28 chunked pushes;
    here waves of node prefetches double the source set each round).
    Returns the number of nodes that newly pulled a copy (nodes already
    holding one don't count). Later ``get``s on those nodes hit their
    local store instead of the owner.

    With ``strict`` (default), a node that could not be reached raises
    ObjectLostError naming it — callers relying on every-node locality
    must not silently proceed without it. ``strict=False`` returns the
    partial count instead. ``return_details`` returns the full reply
    dict (nodes/cached/failed/waves) instead of the count."""
    reply = _runtime.run(
        _runtime.core.broadcast_object(ref, timeout), timeout
    )
    if strict and reply.get("failed"):
        from ray_tpu.exceptions import ObjectLostError

        raise ObjectLostError(
            f"broadcast incomplete ({reply['nodes']} pulled, "
            f"{len(reply['failed'])} failed): {reply['failed']}"
        )
    return reply if return_details else reply["nodes"]


def get(refs, timeout: float | None = _DEFAULT_TIMEOUT):
    single = isinstance(refs, ObjectRef)
    if single:
        refs = [refs]
    if not all(isinstance(r, ObjectRef) for r in refs):
        raise TypeError("ray_tpu.get() takes an ObjectRef or a list of them")
    values = _runtime.run(_runtime.core.get(refs, timeout))
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
):
    return _runtime.run(
        _runtime.core.wait(list(refs), num_returns, timeout)
    )


def kill(actor: "ActorHandle") -> None:
    _runtime.run(_runtime.core.kill_actor(actor._actor_id, actor._addr))


def cancel(ref: ObjectRef, *, force: bool = False) -> bool:
    """Cancel the NORMAL task producing ``ref`` (reference: ray.cancel,
    worker.py). Queued tasks fail fast; running tasks are force-killed
    at the worker (sync execution threads cannot be interrupted — the
    non-force SIGINT path of the reference has no safe analogue here, so
    ``force`` is accepted for API compatibility but both modes kill).
    Returns True if a pending/running task was cancelled; False when the
    task already finished — or when ``ref`` belongs to an ACTOR method
    (actor tasks are not cancellable here; kill the actor instead)."""

    async def do():
        core = _runtime.core
        if ref.owner_addr in (None, core.addr):
            return await core.cancel_task(ref.hex)
        conn = await core._connect(ref.owner_addr)
        reply = await conn.call("cancel_task", oid_hex=ref.hex)
        return bool(reply.get("ok"))

    return _runtime.run(do())


def available_resources() -> dict:
    table = _runtime.run(_runtime.core.head.call("node_table"))
    out: dict[str, float] = {}
    for node in table.values():
        for k, v in node["available"].items():
            out[k] = out.get(k, 0) + v
    return out


def cluster_resources() -> dict:
    table = _runtime.run(_runtime.core.head.call("node_table"))
    out: dict[str, float] = {}
    for node in table.values():
        for k, v in node["resources"].items():
            out[k] = out.get(k, 0) + v
    return out


def nodes() -> list[dict]:
    """Cluster node table: id, address, resources, labels (reference:
    ray.nodes())."""
    table = _runtime.run(_runtime.core.head.call("node_table"))
    return [
        {
            "node_id": nid,
            "addr": n["addr"],
            "resources": n["resources"],
            "available": n["available"],
            "labels": n.get("labels", {}),
            "alive": True,
        }
        for nid, n in table.items()
    ]


# ------------------------------------------------------------- @remote
def _caller_trace_ctx(name: str):
    """Capture the trace context on the CALLER's thread (a driver-side
    tracing.span scope lives in a thread-local that the runtime loop
    cannot see)."""
    from ray_tpu.util import tracing

    return tracing.make_trace_ctx(name)


def _placement_tuple(pg, bundle_index: int):
    if pg is None:
        return None
    return (pg.bundle_node_addr(bundle_index), pg.id, bundle_index)


def _resolve_strategy(strategy, pg, pg_bundle):
    """scheduling_strategy option → (placement_group, bundle, wire spec).
    PlacementGroupSchedulingStrategy folds into the existing placement
    path; affinity/label strategies become a lease-time spec (reference:
    python/ray/util/scheduling_strategies.py)."""
    if strategy is None or strategy == "DEFAULT":
        return pg, pg_bundle, None
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
        to_scheduling_spec,
    )

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return (
            strategy.placement_group,
            strategy.placement_group_bundle_index,
            None,
        )
    return pg, pg_bundle, to_scheduling_spec(strategy)


class ObjectRefGenerator:
    """Iterates a streaming task's yields as they arrive (reference:
    python/ray/_private/object_ref_generator.py:32 ObjectRefGenerator).
    Yields ObjectRefs whose values are already local; works as a sync
    iterator from driver code and an async iterator on the runtime loop.
    """

    def __init__(self, task_id: str):
        self._task_id = task_id
        self._closed = False

    def close(self):
        """Stop consuming: undelivered items are dropped and the producer
        is told to stop at its next report."""
        if self._closed:
            return
        self._closed = True
        # May run from __del__ during interpreter shutdown: never block
        # on a loop that is gone (run_coroutine_threadsafe on a stopped
        # loop would hang forever).
        if (
            _runtime.core is None
            or _runtime.loop is None
            or not _runtime.loop.is_running()
        ):
            return
        try:
            fut = asyncio.run_coroutine_threadsafe(
                _runtime.core.close_generator(self._task_id), _runtime.loop
            )
            # On the runtime loop's own thread (async consumers / GC
            # there), blocking would deadlock the loop — fire and forget.
            if threading.current_thread() is not _runtime.thread:
                fut.result(timeout=2)
        # tpulint: allow(broad-except reason=generator close is best-effort cleanup; the runtime loop may already be stopped and the task gone — both fine outcomes of closing)
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass

    def __del__(self):
        try:
            self.close()
        # tpulint: allow(broad-except reason=__del__ during interpreter teardown must never raise; close() already degrades gracefully while alive)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        entry = _runtime.run(
            _runtime.core.next_generator_item(self._task_id)
        )
        return self._unwrap(entry, StopIteration)

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        entry = await _runtime.core.next_generator_item(self._task_id)
        return self._unwrap(entry, StopAsyncIteration)

    def _unwrap(self, entry, stop_exc):
        kind = entry[0]
        if kind == "done":
            raise stop_exc
        if kind == "error":
            raise entry[1]
        return ObjectRef(entry[1], _runtime.core.addr)


class RemoteFunction:
    def __init__(
        self,
        fn,
        *,
        num_returns=1,
        resources=None,
        max_retries=3,
        placement_group=None,
        placement_group_bundle_index=0,
        runtime_env=None,
        scheduling_strategy=None,
    ):
        self._fn = fn
        self._num_returns = num_returns
        self._resources = resources
        self._max_retries = max_retries
        self._pg = placement_group
        self._pg_bundle = placement_group_bundle_index
        self._runtime_env = runtime_env
        self._strategy = scheduling_strategy
        functools.update_wrapper(self, fn)

    def options(self, **opts):
        opts = _normalize_options(opts)
        merged = {
            "num_returns": self._num_returns,
            "resources": self._resources,
            "max_retries": self._max_retries,
            "placement_group": self._pg,
            "placement_group_bundle_index": self._pg_bundle,
            "runtime_env": self._runtime_env,
            "scheduling_strategy": self._strategy,
        }
        merged.update(opts)
        return RemoteFunction(self._fn, **merged)

    def remote(self, *args, **kwargs):
        pg, pg_bundle, scheduling = _resolve_strategy(
            self._strategy, self._pg, self._pg_bundle
        )
        out = _runtime.run(
            _runtime.core.submit_task(
                self._fn,
                args,
                kwargs,
                num_returns=self._num_returns,
                resources=self._resources,
                max_retries=self._max_retries,
                placement=_placement_tuple(pg, pg_bundle),
                runtime_env=self._runtime_env,
                scheduling=scheduling,
                trace_ctx=_caller_trace_ctx(self.__name__),
            )
        )
        if self._num_returns == "streaming":
            return ObjectRefGenerator(out)
        return out[0] if self._num_returns == 1 else out

    def __call__(self, *a, **kw):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            "use .remote()"
        )


class ActorMethod:
    def __init__(
        self,
        handle: "ActorHandle",
        name: str,
        num_returns=1,
        tensor_transport=None,
    ):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._tensor_transport = tensor_transport

    _UNSET = object()

    def options(self, *, num_returns=_UNSET, tensor_transport=_UNSET):
        """``tensor_transport``: keep this method's return value in the
        actor's device-tensor store and move it point-to-point to
        consumers — True for direct rpc fetch, or a collective group
        name to ride that group's send/recv data plane (reference:
        tensor_transport on actor methods, gpu_object_manager/).
        Unspecified options keep their current values (chainable)."""
        num_returns = (
            self._num_returns if num_returns is self._UNSET else num_returns
        )
        tensor_transport = (
            self._tensor_transport
            if tensor_transport is self._UNSET
            else tensor_transport
        )
        if num_returns == "streaming" and tensor_transport is not None:
            raise ValueError(
                "tensor_transport does not compose with streaming "
                "generators: yielded items go through the normal "
                "result path"
            )
        return ActorMethod(
            self._handle, self._name, num_returns, tensor_transport
        )

    def remote(self, *args, **kwargs):
        target = ActorSubmitTarget(self._handle._actor_id, self._handle._addr)
        out = _runtime.run(
            _runtime.core.submit_task(
                self._name,
                args,
                kwargs,
                num_returns=self._num_returns,
                actor=target,
                tensor_transport=self._tensor_transport,
                trace_ctx=_caller_trace_ctx(self._name),
            )
        )
        if self._num_returns == "streaming":
            return ObjectRefGenerator(out)
        return out[0] if self._num_returns == 1 else out

    def bind(self, *args, **kwargs):
        """Record a compiled-graph edge instead of executing (reference:
        dag building via actor.method.bind, python/ray/dag/class_node.py)."""
        from ray_tpu.dag.node import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: str, addr: str, class_name: str = ""):
        self._actor_id = actor_id
        self._addr = addr
        self._class_name = class_name

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._addr, self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id[:12]}…)"


class ActorClass:
    def __init__(
        self,
        cls,
        *,
        resources=None,
        name=None,
        detached=False,
        placement_group=None,
        placement_group_bundle_index=0,
        max_concurrency=None,
        max_restarts=0,
        runtime_env=None,
        scheduling_strategy=None,
    ):
        self._cls = cls
        self._resources = resources
        self._name = name
        self._detached = detached
        self._pg = placement_group
        self._pg_bundle = placement_group_bundle_index
        self._max_concurrency = max_concurrency
        self._max_restarts = max_restarts
        self._runtime_env = runtime_env
        self._strategy = scheduling_strategy

    def options(self, *, lifetime=None, **opts):
        opts = _normalize_options(opts)
        merged = {
            "resources": self._resources,
            "name": self._name,
            "detached": (lifetime == "detached") or self._detached,
            "placement_group": self._pg,
            "placement_group_bundle_index": self._pg_bundle,
            "max_concurrency": self._max_concurrency,
            "max_restarts": self._max_restarts,
            "runtime_env": self._runtime_env,
            "scheduling_strategy": self._strategy,
        }
        merged.update(opts)
        return ActorClass(self._cls, **merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        pg, pg_bundle, scheduling = _resolve_strategy(
            self._strategy, self._pg, self._pg_bundle
        )
        actor_id, addr = _runtime.run(
            _runtime.core.create_actor(
                self._cls,
                args,
                kwargs,
                name=self._name,
                resources=self._resources,
                detached=self._detached,
                placement=_placement_tuple(pg, pg_bundle),
                max_concurrency=self._max_concurrency,
                max_restarts=self._max_restarts,
                runtime_env=self._runtime_env,
                scheduling=scheduling,
            )
        )
        return ActorHandle(actor_id, addr, self._cls.__name__)


def _normalize_options(options: dict) -> dict:
    """Translate ray-style num_cpus/num_tpus into the resources dict."""
    resources = dict(options.pop("resources", None) or {})
    if "num_cpus" in options:
        resources["CPU"] = float(options.pop("num_cpus"))
    if "num_tpus" in options:
        resources["TPU"] = float(options.pop("num_tpus"))
    if resources:
        options["resources"] = resources
    renv = options.get("runtime_env")
    if renv:
        # Fail bad specs HERE at submission — an invalid env otherwise
        # travels through scheduling and fails per lease attempt deep
        # in the node's locked env builder.
        exclusive = [k for k in ("pip", "uv", "conda") if renv.get(k)]
        if len(exclusive) > 1:
            raise ValueError(
                f"runtime_env: {exclusive} are mutually exclusive — "
                "specify one package manager, not both"
            )
        has_image = bool(renv.get("image_uri")) or bool(
            isinstance(renv.get("container"), dict)
            and renv["container"].get("image")
        )
        if has_image and exclusive:
            # A host-built venv/conda interpreter does not exist inside
            # the image; bake deps into the image instead (reference:
            # image_uri envs exclude pip/conda the same way).
            raise ValueError(
                f"runtime_env: 'container'/'image_uri' cannot combine "
                f"with {exclusive} — install packages in the image"
            )
    return options


def remote(*args, **options):
    """@ray_tpu.remote decorator for functions and classes."""
    options = _normalize_options(options)

    def wrap(target):
        if isinstance(target, type):
            return ActorClass(target, **options)
        return RemoteFunction(target, **options)

    if len(args) == 1 and not options and callable(args[0]):
        return wrap(args[0])
    if args:
        raise TypeError("use @remote or @remote(**options)")
    return wrap


def _submit_system_task(handle: "ActorHandle", fn, *args) -> ObjectRef:
    """Run ``fn(instance, *args)`` as an actor task — the ``@sys:``
    dispatch in core_worker._execute. Shared by compiled graphs and the
    experimental collective API."""
    fn_id = _runtime.run(_runtime.core.export_function(fn))
    target = ActorSubmitTarget(handle._actor_id, handle._addr)
    refs = _runtime.run(
        _runtime.core.submit_task(
            f"@sys:{fn_id}", args, {}, num_returns=1, actor=target
        )
    )
    return refs[0]


def get_actor(name: str) -> ActorHandle:
    reply = _runtime.run(_runtime.core.head.call("get_actor", name=name))
    if not reply["ok"]:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(reply["actor_id"], reply["addr"], reply["class_name"])


def method(**kwargs):
    """Decorator stub for per-method options (reference: ray.method)."""

    def deco(fn):
        return fn

    return deco
