"""Remote spill tier: cross-cluster durability for committed checkpoints.

In-cluster replication and erasure coding survive node and slice loss;
they do not survive "the cluster is gone" (full preemption, region
outage, a deleted TPU pool). The remote tier is the last rung of the
restore ladder — head manifest → in-cluster peers → remote — and the
backing store for `ray_tpu ckpt push/pull`, which makes a checkpoint an
explicit portable artifact (the LocalObjectManager external-storage
spill idea applied to the checkpoint plane).

Backends implement the small ``RemoteTier`` protocol. ``FileTier`` (any
mounted path — NFS, a persistent disk, a tmpdir in tests) is the real,
working backend; ``GcsTier`` is the GCS-shaped stub that activates only
when the cloud SDK is importable, so the wire format is pinned without
adding a dependency.

Every call is deadline-bounded (CKPT_REMOTE_TIMEOUT_S) and failures are
the typed ``RemoteTierError`` — a dead or slow tier degrades saves to
in-cluster-only with a lag alert; it can never hang a save or a restore.
The RAY_TPU_REMOTE_TIER_FAIL chaos knob ('outage' | 'latency:<s>')
injects exactly those failures to prove it.
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import os
import tempfile
import time

logger = logging.getLogger(__name__)


class RemoteTierError(Exception):
    """Typed failure of a remote-tier operation (outage, timeout,
    backend error). Callers degrade; they never see a raw hang."""


class FileTier:
    """Directory-backed tier: ``chunks/<hash>`` plus
    ``manifests/<run>/<step>.r<rank>.json``. Writes are
    tmp-file + rename so a torn upload is never visible."""

    scheme = "file"

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "chunks"), exist_ok=True)
        os.makedirs(os.path.join(root, "manifests"), exist_ok=True)
        os.makedirs(os.path.join(root, "objects"), exist_ok=True)

    def _chunk_path(self, hex_hash: str) -> str:
        return os.path.join(self.root, "chunks", hex_hash)

    def _manifest_path(self, run: str, step: int, rank: int) -> str:
        return os.path.join(
            self.root, "manifests", run, f"{int(step):012d}.r{rank}.json"
        )

    def _write_atomic(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # ------------------------------------------------------------ chunks
    def has_chunk(self, hex_hash: str) -> bool:
        return os.path.exists(self._chunk_path(hex_hash))

    def put_chunk(self, hex_hash: str, data: bytes) -> None:
        if not self.has_chunk(hex_hash):
            self._write_atomic(self._chunk_path(hex_hash), bytes(data))

    def get_chunk(self, hex_hash: str) -> bytes | None:
        try:
            with open(self._chunk_path(hex_hash), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    # --------------------------------------------------------- manifests
    def put_manifest(self, run: str, step: int, rank: int, doc: dict):
        self._write_atomic(
            self._manifest_path(run, step, rank),
            json.dumps(doc).encode(),
        )

    def get_manifest(self, run: str, step: int, rank: int) -> dict | None:
        try:
            with open(self._manifest_path(run, step, rank)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def list_steps(self, run: str) -> dict[int, list[int]]:
        """step → sorted ranks present (completeness is judged against
        the world size recorded inside the manifests)."""
        d = os.path.join(self.root, "manifests", run)
        out: dict[int, list[int]] = {}
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            stem = name[: -len(".json")]
            step_s, _, rank_s = stem.partition(".r")
            try:
                out.setdefault(int(step_s), []).append(int(rank_s))
            except ValueError:
                continue
        return {s: sorted(rs) for s, rs in out.items()}

    # ------------------------------------------- general objects (drain)
    def put_object(self, oid_hex: str, data: bytes) -> None:
        self._write_atomic(
            os.path.join(self.root, "objects", oid_hex), bytes(data)
        )

    def get_object(self, oid_hex: str) -> bytes | None:
        try:
            with open(os.path.join(self.root, "objects", oid_hex), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None


class GcsTier(FileTier):
    """GCS-shaped stub: same layout keyed under gs://bucket/prefix. The
    real client is imported lazily; without the SDK baked into the image
    the constructor raises a typed error instead of half-working."""

    scheme = "gs"

    def __init__(self, uri: str):
        try:
            from google.cloud import storage  # noqa: F401
        except ImportError as e:
            raise RemoteTierError(
                f"CKPT_REMOTE_TIER={uri!r} needs google-cloud-storage, "
                "which this image does not bundle — use a mounted path "
                "(FileTier) or bake the SDK in"
            ) from e
        raise RemoteTierError(
            "GcsTier upload client not implemented in this build"
        )


class _ChaosTier:
    """REMOTE_TIER_FAIL wrapper: 'outage' raises on every call,
    'latency:<s>' sleeps first (the deadline then converts long sleeps
    into timeouts — exactly the slow-backend failure mode)."""

    def __init__(self, inner, spec: str):
        self._inner = inner
        self._spec = spec

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr
        spec = self._spec

        def chaoticed(*a, **kw):
            mode, _, arg = spec.partition(":")
            if mode == "outage":
                raise RemoteTierError(
                    f"remote tier outage (chaos) during {name}"
                )
            if mode == "latency":
                time.sleep(float(arg or 1.0))
            return attr(*a, **kw)

        return chaoticed


class _BoundedTier:
    """Deadline wrapper: every tier call runs on a worker thread with a
    CKPT_REMOTE_TIMEOUT_S budget; overruns and backend exceptions both
    surface as RemoteTierError. The thread is shared and lazily built —
    remote uploads already happen off the step loop."""

    def __init__(self, inner):
        self._inner = inner
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-remote"
        )

    @property
    def scheme(self) -> str:
        return self._inner.scheme

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def bounded(*a, **kw):
            from ray_tpu._private import config

            deadline = float(config.get("CKPT_REMOTE_TIMEOUT_S"))
            fut = self._pool.submit(attr, *a, **kw)
            try:
                return fut.result(timeout=deadline)
            except concurrent.futures.TimeoutError:
                fut.cancel()
                raise RemoteTierError(
                    f"remote tier {name} exceeded {deadline}s deadline"
                ) from None
            except RemoteTierError:
                raise
            except Exception as e:  # noqa: BLE001 - typed boundary
                raise RemoteTierError(
                    f"remote tier {name} failed: {e!r}"
                ) from e

        return bounded


_cached: tuple[str, object] | None = None


def get_tier(spec: str | None = None):
    """Resolve CKPT_REMOTE_TIER to a deadline-bounded tier (None when
    unset). '' → None; 'gs://…' → GcsTier; anything else (plain path or
    file:// URI) → FileTier. The chaos wrapper applies INSIDE the
    deadline so injected latency is bounded like real latency."""
    global _cached
    from ray_tpu._private import config

    raw = spec if spec is not None else str(config.get("CKPT_REMOTE_TIER"))
    raw = (raw or "").strip()
    if not raw:
        return None
    chaos = str(config.get("REMOTE_TIER_FAIL") or "").strip()
    key = f"{raw}|{chaos}"
    if _cached is not None and _cached[0] == key:
        return _cached[1]
    if raw.startswith("gs://"):
        inner = GcsTier(raw)
    else:
        path = raw[len("file://"):] if raw.startswith("file://") else raw
        inner = FileTier(path)
    if chaos:
        inner = _ChaosTier(inner, chaos)
    tier = _BoundedTier(inner)
    _cached = (key, tier)
    return tier


def reset_tier_cache() -> None:
    """Test hook: drop the resolved-tier cache after config changes."""
    global _cached
    _cached = None


# General-object framing: a spilled/evacuated object is the logical
# segment stream (inband ++ buffers) plus its segment lengths, packed
# into one blob so any tier backend stays a dumb byte store.
def pack_object(seg_lens: list[int], payload: bytes) -> bytes:
    import struct

    header = json.dumps([int(n) for n in seg_lens]).encode()
    return struct.pack(">I", len(header)) + header + payload


def unpack_object(blob: bytes) -> tuple[list[int], bytes]:
    import struct

    (hlen,) = struct.unpack_from(">I", blob, 0)
    seg_lens = json.loads(blob[4 : 4 + hlen].decode())
    return [int(n) for n in seg_lens], blob[4 + hlen:]


# ------------------------------------------------------------ push / pull
def push_checkpoint(
    run: str, step: int | None = None, tier=None
) -> dict:
    """Copy a committed checkpoint (newest complete step by default)
    from the cluster to the remote tier — the explicit `ray_tpu ckpt
    push` path for making a checkpoint portable before teardown."""
    # NOTE: the package re-exports restore() the FUNCTION as
    # `ray_tpu.checkpoint.restore`; import the helper by symbol.
    from ray_tpu.checkpoint.restore import _fetch_chunks
    from ray_tpu.checkpoint.saver import _runtime

    tier = tier or get_tier()
    if tier is None:
        raise RemoteTierError("no remote tier configured (CKPT_REMOTE_TIER)")
    rt = _runtime()
    reply = rt.run(
        rt.core.head.call("ckpt_manifest", run=run, step=step)
    )
    if not reply.get("ok"):
        raise RemoteTierError(reply.get("error", "no manifest"))
    entries = reply["entries"]
    parity = reply.get("parity", [])
    from ray_tpu.checkpoint.manifest import manifest_chunks

    hashes = sorted(manifest_chunks(entries))
    chunks = rt.run(
        _fetch_chunks(
            rt, hashes, reply.get("locations", {}), parity=parity
        )
    )
    # Parity shards ride along best-effort: a lost parity chunk must not
    # block the push (the data is whole — the head's repair loop can
    # re-encode parity later), it just ships less redundancy.
    from ray_tpu.checkpoint.manifest import parity_chunks as _pchunks
    from ray_tpu.exceptions import ObjectLostError

    for ph in sorted(_pchunks(parity)):
        try:
            pdata = rt.run(
                _fetch_chunks(
                    rt, [ph], reply.get("locations", {})
                )
            )
            chunks.update(pdata)
        except ObjectLostError:
            logger.warning(
                "push: parity chunk %s… unavailable in-cluster; "
                "pushing without it", ph[:12]
            )
    uploaded = 0
    for h, data in chunks.items():
        if not tier.has_chunk(h):
            tier.put_chunk(h, data)
            uploaded += 1
    # One merged world=1 manifest: pull needs no knowledge of the
    # original rank layout (the shards keep their index specs).
    tier.put_manifest(
        run,
        int(reply["step"]),
        0,
        {
            "run": run,
            "step": int(reply["step"]),
            "rank": 0,
            "world": 1,
            "entries": list(entries.values()),
            "parity": parity,
            "metrics": {},
            "ts": time.time(),
        },
    )
    return {
        "ok": True,
        "run": run,
        "step": int(reply["step"]),
        "chunks": len(hashes),
        "uploaded": uploaded,
    }


def pull_checkpoint(
    run: str, step: int | None = None, tier=None
) -> dict:
    """Re-seed the cluster from the remote tier: insert every chunk into
    the local shard store and commit the manifest(s) to the head — after
    this, restore() works exactly as if the checkpoint had been saved
    in-cluster (the 'cluster was gone' recovery path)."""
    from ray_tpu.checkpoint.manifest import manifest_chunks
    from ray_tpu.checkpoint.saver import _runtime
    from ray_tpu.checkpoint.store import ShardStore

    tier = tier or get_tier()
    if tier is None:
        raise RemoteTierError("no remote tier configured (CKPT_REMOTE_TIER)")
    rt = _runtime()
    steps = tier.list_steps(run)
    if not steps:
        raise RemoteTierError(f"remote tier has no checkpoints for {run!r}")
    pick = int(step) if step is not None else max(steps)
    if pick not in steps:
        raise RemoteTierError(f"remote tier has no step {pick} for {run!r}")
    docs = [
        tier.get_manifest(run, pick, r)
        for r in steps[pick]
    ]
    docs = [d for d in docs if d is not None]
    world = max((int(d.get("world", 1)) for d in docs), default=1)
    if not docs or {int(d["rank"]) for d in docs} < set(range(world)):
        raise RemoteTierError(
            f"remote manifest set for {run!r} step {pick} is incomplete"
        )
    from ray_tpu.checkpoint.manifest import parity_chunks as _pchunks

    store = ShardStore(rt.core.store)
    own_addr = rt.core.node_addr or rt.core.addr
    inserted = 0
    total = 0
    locations: dict[str, list[str]] = {}
    for doc in docs:
        parity_hs = _pchunks(doc.get("parity"))
        for h in sorted(
            manifest_chunks(doc["entries"]) | parity_hs
        ):
            if h in locations:
                continue
            if store.has_chunk(h):
                total += 1
                locations[h] = [own_addr]
                continue
            data = tier.get_chunk(h)
            if data is None:
                if h in parity_hs:
                    # Parity is redundancy, not state: a tier missing a
                    # parity shard still yields a usable checkpoint (the
                    # head's repair loop re-encodes it in-cluster).
                    logger.warning(
                        "pull: parity chunk %s… missing from the remote "
                        "tier; head repair will re-encode it", h[:12]
                    )
                    continue
                raise RemoteTierError(
                    f"remote tier missing chunk {h[:12]} for {run!r} "
                    f"step {pick}"
                )
            store.put_chunk(h, data)
            inserted += 1
            total += 1
            locations[h] = [own_addr]
    for doc in docs:
        rt.run(
            rt.core.head.call(
                "ckpt_commit",
                run=run,
                step=pick,
                rank=int(doc["rank"]),
                world=int(doc.get("world", 1)),
                entries=doc["entries"],
                parity=doc.get("parity", []),
                locations=locations,
                metrics=doc.get("metrics", {}),
            )
        )
    return {
        "ok": True,
        "run": run,
        "step": pick,
        "chunks": total,
        "inserted": inserted,
    }
