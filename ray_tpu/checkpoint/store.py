"""Content-addressed chunk layer over the per-node object store.

Checkpoint bytes are split into fixed-size chunks and keyed by content
hash (sha256, truncated to the ObjectID width), so the store
deduplicates by construction: a leaf that didn't change between
consecutive checkpoints (embedding tables, frozen layers, optimizer
slots that didn't update) re-produces the same hashes and writes zero
new bytes. Chunks live in the SAME node object store that task results
use (`runtime/object_store.py`), so the existing serving RPCs
(get_object_meta / get_object_chunk), the pull/transfer path, and the
spill-to-disk machinery all apply to checkpoint data for free.
"""

from __future__ import annotations

import hashlib
import logging

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import Serialized
from ray_tpu.util.metrics import Counter

logger = logging.getLogger(__name__)

CORRUPT_CHUNKS = Counter(
    "ray_tpu_ckpt_corrupt_chunks_total",
    "checkpoint chunks whose stored bytes failed the content-hash check",
)

# Chunk keys are truncated sha256 digests widened to the ObjectID wire
# format so every existing object RPC can carry them.
CHUNK_HEX_LEN = ObjectID.LENGTH * 2

# In-cluster checkpoints are addressed by URI, not directory: the train
# resume plumbing (latest_checkpoint strings) carries these through
# unchanged call sites.
CKPT_URI_PREFIX = "ckpt://"


def is_ckpt_uri(path) -> bool:
    return isinstance(path, str) and path.startswith(CKPT_URI_PREFIX)


def make_uri(run: str, step: int) -> str:
    return f"{CKPT_URI_PREFIX}{run}/{int(step)}"


def parse_uri(uri: str) -> tuple[str, int]:
    if not is_ckpt_uri(uri):
        raise ValueError(f"not a checkpoint uri: {uri!r}")
    run, _, step = uri[len(CKPT_URI_PREFIX):].rpartition("/")
    return run, int(step)


def chunk_hash(data) -> str:
    return hashlib.sha256(data).hexdigest()[:CHUNK_HEX_LEN]


def chunk_oid(hex_hash: str) -> ObjectID:
    return ObjectID.from_hex(hex_hash)


def default_chunk_bytes() -> int:
    from ray_tpu._private import config

    return int(config.get("CKPT_CHUNK_BYTES"))


def _maybe_corrupt(hex_hash: str, data: bytes) -> bytes:
    """Chaos hook: CKPT_CORRUPT='prefix:prob' flips a byte in matching
    chunks. The decision is a deterministic hash of the chunk id, so a
    corrupted chunk stays corrupted across retries — the reader can
    never win by re-reading, only by reconstructing."""
    from ray_tpu._private import config

    spec = config.get("CKPT_CORRUPT")
    if not spec:
        return data
    prefix, _, prob = spec.partition(":")
    if prefix and not hex_hash.startswith(prefix):
        return data
    die = int(hashlib.sha256(("corrupt:" + hex_hash).encode()).hexdigest()[:8], 16)
    if die / 0xFFFFFFFF >= float(prob or 1.0):
        return data
    buf = bytearray(data)
    if buf:
        buf[len(buf) // 2] ^= 0xFF
    return bytes(buf)


class ShardStore:
    """Thin content-addressed facade over one node's ObjectStore."""

    def __init__(self, store):
        self._store = store

    def put_bytes(
        self, data, chunk_bytes: int | None = None
    ) -> tuple[list[str], int]:
        """Write ``data`` (bytes/memoryview) as content-addressed chunks.
        Returns ``(chunk_hashes, new_bytes)`` where new_bytes counts only
        chunks that were not already present (the dedup ledger)."""
        n = chunk_bytes or default_chunk_bytes()
        mv = memoryview(data).cast("B")
        hashes: list[str] = []
        new_bytes = 0
        for off in range(0, max(1, len(mv)), n):
            piece = mv[off : off + n]
            h = chunk_hash(piece)
            hashes.append(h)
            oid = chunk_oid(h)
            if not self._store.contains(oid):
                new_bytes += self._store.put(
                    oid, Serialized(bytes(piece), [])
                )
        return hashes, new_bytes

    def has_chunk(self, hex_hash: str) -> bool:
        return self._store.contains(chunk_oid(hex_hash))

    def get_chunk(self, hex_hash: str) -> bytes | None:
        from ray_tpu._private import config

        oid = chunk_oid(hex_hash)
        view = self._store.get(oid)
        if view is None:
            return None
        try:
            data = bytes(view.inband)
        finally:
            # Checkpoint restores touch thousands of chunks; pinning
            # every mmap would hold the whole checkpoint in shm.
            self._store.release(oid)
        data = _maybe_corrupt(hex_hash, data)
        if config.get("CKPT_VERIFY_READS") and chunk_hash(data) != hex_hash:
            # Bit rot (or the chaos knob above). A corrupt local copy is
            # indistinguishable from a missing one to callers: they fall
            # through to peers / parity reconstruction, which re-caches a
            # good copy over this one.
            CORRUPT_CHUNKS.inc()
            logger.warning("ckpt chunk %s failed content-hash check; "
                           "treating as missing", hex_hash[:12])
            return None
        return data

    def put_chunk(self, hex_hash: str, data: bytes) -> int:
        oid = chunk_oid(hex_hash)
        if self._store.contains(oid):
            return 0
        return self._store.put(oid, Serialized(bytes(data), []))

    def delete_chunk(self, hex_hash: str) -> None:
        self._store.delete(chunk_oid(hex_hash))
