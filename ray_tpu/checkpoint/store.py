"""Content-addressed chunk layer over the per-node object store.

Checkpoint bytes are split into fixed-size chunks and keyed by content
hash (sha256, truncated to the ObjectID width), so the store
deduplicates by construction: a leaf that didn't change between
consecutive checkpoints (embedding tables, frozen layers, optimizer
slots that didn't update) re-produces the same hashes and writes zero
new bytes. Chunks live in the SAME node object store that task results
use (`runtime/object_store.py`), so the existing serving RPCs
(get_object_meta / get_object_chunk), the pull/transfer path, and the
spill-to-disk machinery all apply to checkpoint data for free.
"""

from __future__ import annotations

import hashlib

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.serialization import Serialized

# Chunk keys are truncated sha256 digests widened to the ObjectID wire
# format so every existing object RPC can carry them.
CHUNK_HEX_LEN = ObjectID.LENGTH * 2

# In-cluster checkpoints are addressed by URI, not directory: the train
# resume plumbing (latest_checkpoint strings) carries these through
# unchanged call sites.
CKPT_URI_PREFIX = "ckpt://"


def is_ckpt_uri(path) -> bool:
    return isinstance(path, str) and path.startswith(CKPT_URI_PREFIX)


def make_uri(run: str, step: int) -> str:
    return f"{CKPT_URI_PREFIX}{run}/{int(step)}"


def parse_uri(uri: str) -> tuple[str, int]:
    if not is_ckpt_uri(uri):
        raise ValueError(f"not a checkpoint uri: {uri!r}")
    run, _, step = uri[len(CKPT_URI_PREFIX):].rpartition("/")
    return run, int(step)


def chunk_hash(data) -> str:
    return hashlib.sha256(data).hexdigest()[:CHUNK_HEX_LEN]


def chunk_oid(hex_hash: str) -> ObjectID:
    return ObjectID.from_hex(hex_hash)


def default_chunk_bytes() -> int:
    from ray_tpu._private import config

    return int(config.get("CKPT_CHUNK_BYTES"))


class ShardStore:
    """Thin content-addressed facade over one node's ObjectStore."""

    def __init__(self, store):
        self._store = store

    def put_bytes(
        self, data, chunk_bytes: int | None = None
    ) -> tuple[list[str], int]:
        """Write ``data`` (bytes/memoryview) as content-addressed chunks.
        Returns ``(chunk_hashes, new_bytes)`` where new_bytes counts only
        chunks that were not already present (the dedup ledger)."""
        n = chunk_bytes or default_chunk_bytes()
        mv = memoryview(data).cast("B")
        hashes: list[str] = []
        new_bytes = 0
        for off in range(0, max(1, len(mv)), n):
            piece = mv[off : off + n]
            h = chunk_hash(piece)
            hashes.append(h)
            oid = chunk_oid(h)
            if not self._store.contains(oid):
                new_bytes += self._store.put(
                    oid, Serialized(bytes(piece), [])
                )
        return hashes, new_bytes

    def has_chunk(self, hex_hash: str) -> bool:
        return self._store.contains(chunk_oid(hex_hash))

    def get_chunk(self, hex_hash: str) -> bytes | None:
        oid = chunk_oid(hex_hash)
        view = self._store.get(oid)
        if view is None:
            return None
        try:
            return bytes(view.inband)
        finally:
            # Checkpoint restores touch thousands of chunks; pinning
            # every mmap would hold the whole checkpoint in shm.
            self._store.release(oid)

    def put_chunk(self, hex_hash: str, data: bytes) -> int:
        oid = chunk_oid(hex_hash)
        if self._store.contains(oid):
            return 0
        return self._store.put(oid, Serialized(bytes(data), []))

    def delete_chunk(self, hex_hash: str) -> None:
        self._store.delete(chunk_oid(hex_hash))
