"""Async snapshot-offload checkpointing.

``AsyncCheckpointer.save()`` pays only the device→host copy on the step
loop (double-buffered host arrays, bounded to ONE in-flight snapshot)
and returns; a background writer thread then serializes the owned
shards into the content-addressed chunk store, replicates each chunk to
R-1 peer nodes over the existing object-transfer path, and commits the
manifest to the head. The manifest commit is the linearization point:
until it lands, the checkpoint does not exist, so a worker killed
mid-persist leaves the previous checkpoint fully restorable and never
exposes a partial one.

The emergency-checkpoint path (node drain notice) reuses whatever
snapshot is already offloaded: the drain window pays only the persist,
never the copy — ``wait()``/``wait_pending()`` is the barrier.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref

import numpy as np

from ray_tpu.checkpoint import erasure as _erasure
from ray_tpu.checkpoint import manifest as _manifest
from ray_tpu.checkpoint.store import ShardStore, chunk_hash, make_uri
from ray_tpu.util.metrics import Counter, Gauge, Histogram

logger = logging.getLogger("ray_tpu.checkpoint")

CKPT_BYTES = Counter(
    "ray_tpu_ckpt_bytes_total",
    "checkpoint bytes by kind: 'logical' = snapshot size, 'written' = "
    "new chunk bytes after dedup",
    tag_keys=("job", "kind"),
)
DEDUP_RATIO = Gauge(
    "ray_tpu_ckpt_dedup_ratio",
    "fraction of the last checkpoint's bytes served by existing chunks",
    tag_keys=("job",),
)
REPLICATION_LAG = Gauge(
    "ray_tpu_ckpt_replication_lag_seconds",
    "snapshot-offload to manifest-commit latency of the last checkpoint",
    tag_keys=("job",),
)
PHASE_SECONDS = Histogram(
    "ray_tpu_ckpt_phase_seconds",
    "checkpoint pipeline time by phase (snapshot is the only one the "
    "step loop pays)",
    boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
    tag_keys=("job", "phase"),
)
REMOTE_LAG = Gauge(
    "ray_tpu_ckpt_remote_lag_seconds",
    "snapshot-offload to remote-tier-upload latency of the last "
    "checkpoint (the replication-lag twin for the durable tier)",
    tag_keys=("job",),
)
REMOTE_ERRORS = Counter(
    "ray_tpu_ckpt_remote_errors_total",
    "remote-tier upload failures (saves keep committing in-cluster)",
    tag_keys=("job",),
)
REMOTE_ALERT = Gauge(
    "ray_tpu_ckpt_remote_alert",
    "1 while the newest committed checkpoint has NOT reached the remote "
    "tier (outage / lag alert), 0 once it has",
    tag_keys=("job",),
)

# Live checkpointers in this process: the emergency-unwind barrier
# (session.report → wait_pending) must reach them without the train loop
# having to thread handles around.
_live: "weakref.WeakSet[AsyncCheckpointer]" = weakref.WeakSet()

# Step-loop stall seconds accumulated since the last report(): the
# goodput ledger charges ONLY this (the snapshot copy), not the
# background persist that overlaps compute.
_stall_lock = threading.Lock()
_stall_s = 0.0


def _add_stall(seconds: float) -> None:
    global _stall_s
    with _stall_lock:
        _stall_s += seconds


def take_step_stall_seconds() -> float:
    """Drain the accumulated checkpoint stall (called by report())."""
    global _stall_s
    with _stall_lock:
        s = _stall_s
        _stall_s = 0.0
    return s


def wait_pending(timeout: float | None = None) -> None:
    """Barrier every in-flight checkpoint in this process (attempt end,
    emergency unwind). Raises the first persist failure."""
    for cp in list(_live):
        cp.wait(timeout=timeout)


def _runtime():
    import ray_tpu.api as api

    rt = api._runtime
    if getattr(rt, "core", None) is None:
        raise RuntimeError(
            "ray_tpu.checkpoint needs an initialized runtime "
            "(ray_tpu.init) — the shard store lives in the node object "
            "store and manifests commit to the head"
        )
    return rt


class AsyncCheckpointer:
    """Distributed, replicated checkpoints for one training run.

    ::

        cp = ray_tpu.checkpoint.AsyncCheckpointer()   # run/rank from ctx
        for step in ...:
            state = train_step(state, batch)
            uri = cp.save(step, state)      # device→host copy only
            train.report(metrics, checkpoint=uri)
        cp.wait()                           # end-of-attempt barrier
    """

    def __init__(
        self,
        run: str | None = None,
        *,
        replication: int | None = None,
        rank: int | None = None,
        world: int | None = None,
        local_prefixes: tuple[str, ...] = (),
        erasure: str | tuple[int, int] | None = None,
    ):
        from ray_tpu._private import config
        from ray_tpu.train import session

        ctx = session._context
        self.run = run or (ctx.experiment_name if ctx else "default")
        self.rank = rank if rank is not None else (ctx.rank if ctx else 0)
        self.world = (
            world if world is not None else (ctx.world_size if ctx else 1)
        )
        self.replication = int(
            replication
            if replication is not None
            else config.get("CKPT_REPLICATION")
        )
        # (k, m) or None. With erasure on, each group's k data + m parity
        # chunks land on DISTINCT nodes (slice-diverse order) at
        # `replication` copies each — replication=1 is the intended
        # pairing: (k+m)/k bytes, any m node losses reconstructible.
        if erasure is None:
            self.erasure = _erasure.parse_spec(config.get("CKPT_ERASURE"))
        elif isinstance(erasure, str):
            self.erasure = _erasure.parse_spec(erasure)
        else:
            self.erasure = erasure
        # Subtree prefixes that are already per-rank shards (the ZeRO
        # optimizer state): persisted as-held, never re-partitioned
        # (manifest.owned_items local_prefixes semantics).
        self.local_prefixes = tuple(local_prefixes)
        # key → list[(index_spec, host buffer)]: the double buffer. save()
        # only runs while no persist is in flight, so the writer thread
        # and the copy never touch the same buffers concurrently.
        self._host: dict[str, list[tuple[list | None, np.ndarray]]] = {}
        self._thread: threading.Thread | None = None
        self._err: Exception | None = None
        # Stats of the last completed persist (tests + dashboards).
        self.last: dict = {}
        # Host-side byte claim in the device-memory ledger: the
        # double-buffered snapshot arrays are the checkpoint
        # subsystem's big host tenant (one full model copy in RAM).
        from ray_tpu.runtime import memory as _rmem

        self._mem_reg = _rmem.track(
            f"checkpoint.saver.{self.run}.r{self.rank}",
            kind="ckpt_host_buffer",
            device=False,
        )
        _live.add(self)

    # ------------------------------------------------------------- save
    def save(self, step: int, state, metrics: dict | None = None) -> str:
        """Snapshot ``state`` and return immediately; persistence +
        replication + manifest commit happen in the background. Bounded
        by one in-flight snapshot: a second save first waits out the
        previous persist (backpressure, not a queue)."""
        t0 = time.perf_counter()
        self.wait()
        snapshot: list[tuple[str, tuple, list]] = []
        for key, leaf in _manifest.owned_items(
            state, self.rank, self.world,
            local_prefixes=self.local_prefixes,
        ):
            # Global shape comes from the LEAF (a process-sharded
            # array's local windows may not reach the far edge); a
            # shapeless leaf (python scalar/list) uses its host copy's.
            shape_attr = getattr(leaf, "shape", None)
            shards = _manifest.local_shards(leaf)
            global_shape = (
                tuple(shape_attr)
                if shape_attr is not None
                else tuple(shards[0][1].shape)
            )
            bufs = self._host.get(key)
            if (
                bufs is None
                or len(bufs) != len(shards)
                or any(
                    b.shape != a.shape or b.dtype != a.dtype
                    for (_, b), (_, a) in zip(bufs, shards)
                )
            ):
                bufs = [
                    (idx, np.array(arr, copy=True)) for idx, arr in shards
                ]
                self._host[key] = bufs
            else:
                for (_, dst), (idx, src) in zip(bufs, shards):
                    np.copyto(dst, src)
                self._host[key] = bufs = [
                    (idx, dst) for (_, dst), (idx, _) in zip(bufs, shards)
                ]
            snapshot.append((key, global_shape, bufs))
        self._mem_reg.update(
            sum(
                buf.nbytes
                for _key, _shape, bufs in snapshot
                for _idx, buf in bufs
            )
        )
        snap_s = time.perf_counter() - t0
        _add_stall(snap_s)
        PHASE_SECONDS.observe(snap_s, tags={"job": self.run, "phase": "snapshot"})
        from ray_tpu.util import tracing

        tracing.emit_span(
            "ckpt:snapshot",
            time.time() - snap_s,
            snap_s,
            train_job=self.run,
            ckpt_step=int(step),
        )
        self._err = None
        self._thread = threading.Thread(
            target=self._persist,
            args=(int(step), snapshot, dict(metrics or {}), time.time()),
            name=f"ckpt-persist-{self.run}",
            daemon=True,
        )
        self._thread.start()
        return make_uri(self.run, step)

    def wait(self, timeout: float | None = None) -> None:
        """Block until the in-flight persist (if any) commits; raise its
        failure. This is the attempt-end / emergency barrier."""
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    f"checkpoint persist for run {self.run!r} still "
                    f"running after {timeout}s"
                )
            self._thread = None
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    @property
    def in_flight(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ---------------------------------------------------------- persist
    def _persist(self, step, snapshot, metrics, t_offloaded) -> None:
        try:
            self._persist_inner(step, snapshot, metrics, t_offloaded)
        except Exception as e:  # noqa: BLE001 - surfaced via wait()
            logger.warning(
                "checkpoint persist failed for %s step %s: %r",
                self.run,
                step,
                e,
            )
            self._err = e

    def _persist_inner(self, step, snapshot, metrics, t_offloaded) -> None:
        from ray_tpu._private import config

        rt = _runtime()
        shard_store = ShardStore(rt.core.store)
        own_addr = rt.core.node_addr or rt.core.addr
        t0 = time.perf_counter()
        entries: list[dict] = []
        locations: dict[str, list[str]] = {}
        logical = 0
        new_bytes = 0
        for key, global_shape, bufs in snapshot:
            shards = []
            for index, arr in bufs:
                flat = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
                hashes, nb = shard_store.put_bytes(flat)
                new_bytes += nb
                logical += flat.nbytes
                for h in hashes:
                    locations.setdefault(h, [own_addr])
                shards.append(
                    {
                        "index": index,
                        "chunks": hashes,
                        "nbytes": int(flat.nbytes),
                    }
                )
            entries.append(
                {
                    "key": key,
                    "shape": list(global_shape),
                    "dtype": bufs[0][1].dtype.name,
                    "shards": shards,
                }
            )
        parity: list[dict] = []
        if self.erasure:
            parity = self._encode_parity(
                shard_store, list(locations), own_addr, locations
            )
        write_s = time.perf_counter() - t0
        delay = config.get("CKPT_PERSIST_DELAY_S")
        if delay:
            # Chaos hook: hold the window between chunk writes and the
            # manifest commit open so kill-mid-save tests can land inside
            # the exact race the commit protocol closes.
            time.sleep(float(delay))

        t1 = time.perf_counter()
        all_chunks = list(locations)
        deletable: list[str] = []
        if self.erasure:
            replicated, deletable = self._distribute(
                rt, own_addr, locations, parity
            )
        else:
            replicated = self._replicate(
                rt, all_chunks, own_addr, locations
            )
        repl_s = time.perf_counter() - t1

        t2 = time.perf_counter()
        reply = rt.run(
            rt.core.head.call(
                "ckpt_commit",
                run=self.run,
                step=int(step),
                rank=self.rank,
                world=self.world,
                entries=entries,
                parity=parity,
                locations=locations,
                metrics=metrics,
            )
        )
        commit_s = time.perf_counter() - t2
        remote = self._remote_offload(
            shard_store, step, entries, parity, all_chunks, metrics,
            t_offloaded,
        )
        # Erasure placement frees the writer's copy of chunks that landed
        # elsewhere — that is where the ≤(k+m)/k stored-bytes ratio comes
        # from. Deletion strictly AFTER commit + remote upload: until
        # then the local copy is the only confirmed-readable one.
        for h in deletable:
            shard_store.delete_chunk(h)
        lag = time.time() - t_offloaded

        tags = {"job": self.run}
        CKPT_BYTES.inc(logical, tags={"job": self.run, "kind": "logical"})
        CKPT_BYTES.inc(new_bytes, tags={"job": self.run, "kind": "written"})
        if logical:
            DEDUP_RATIO.set(1.0 - new_bytes / logical, tags=tags)
        REPLICATION_LAG.set(lag, tags=tags)
        PHASE_SECONDS.observe(write_s, tags={"job": self.run, "phase": "write"})
        PHASE_SECONDS.observe(
            repl_s, tags={"job": self.run, "phase": "replicate"}
        )
        PHASE_SECONDS.observe(
            commit_s, tags={"job": self.run, "phase": "commit"}
        )
        from ray_tpu.util import tracing

        tracing.emit_span(
            "ckpt:persist",
            t_offloaded,
            lag,
            train_job=self.run,
            ckpt_step=int(step),
            bytes=logical,
            new_bytes=new_bytes,
        )
        self.last = {
            "step": int(step),
            "uri": make_uri(self.run, step),
            "logical_bytes": logical,
            "new_bytes": new_bytes,
            "chunks": len(all_chunks),
            "parity_groups": len(parity),
            "replicas": replicated,
            "complete": bool(reply.get("complete")),
            "persist_s": write_s + repl_s + commit_s,
            "replication_lag_s": lag,
            "remote": remote,
        }

    # ---------------------------------------------------------- erasure
    def _encode_parity(
        self, shard_store, data_hashes, own_addr, locations
    ) -> list[dict]:
        """Group this rank's chunks k at a time and store m parity
        chunks per group (content-addressed like any other chunk, so a
        repeated save dedups its parity too). Returns the manifest
        parity-group records: {"data", "parity", "lens"}."""
        k, m = self.erasure
        groups: list[dict] = []
        for grp in _erasure.plan_groups(data_hashes, k):
            datas = []
            for h in grp:
                d = shard_store.get_chunk(h)
                if d is None:
                    # Only reachable under the corrupt-chunk chaos knob:
                    # put_bytes just wrote these. Skip the group — its
                    # members keep plain replication protection.
                    logger.warning(
                        "parity encode: chunk %s unreadable, skipping "
                        "group", h[:12]
                    )
                    datas = None
                    break
                datas.append(d)
            if datas is None:
                continue
            phashes = []
            for p in _erasure.encode(datas, m):
                ph = chunk_hash(p)
                shard_store.put_chunk(ph, p)
                locations.setdefault(ph, [own_addr])
                phashes.append(ph)
            groups.append(
                {
                    "data": list(grp),
                    "parity": phashes,
                    "lens": [len(d) for d in datas],
                }
            )
        return groups

    def _distribute(
        self, rt, own_addr, locations, parity_groups
    ) -> tuple[int, list[str]]:
        """Erasure placement: spread each group's k+m members over
        DISTINCT nodes (the peer-candidate order is slice-interleaved,
        so consecutive targets sit on different slices — any m node OR
        slice losses leave ≥k members). Each member gets
        ``self.replication`` copies (1 is the intended pairing).

        Returns (peer pushes confirmed, chunks whose local copy became
        redundant and can be deleted after commit)."""
        targets = [own_addr] + self._peer_candidates(rt, own_addr)
        if len(targets) == 1:
            return 0, []  # single node: everything stays local
        assigned: dict[str, list[str]] = {}
        for g, grp in enumerate(parity_groups):
            members = list(grp["data"]) + list(grp["parity"])
            for i, h in enumerate(members):
                if h in assigned:
                    continue  # dedup across groups
                assigned[h] = [
                    targets[(g + i + r) % len(targets)]
                    for r in range(min(self.replication, len(targets)))
                ]
        # Chunks outside any group (corrupt-chaos skip) stay local.
        per_target: dict[str, list[str]] = {}
        for h, tgts in assigned.items():
            for t in tgts:
                if t != own_addr:
                    per_target.setdefault(t, []).append(h)
        pushed: dict[str, set[str]] = {}
        confirmed = 0
        for peer, hs in per_target.items():
            try:
                conn = rt.run(rt.core._connect(peer))
                reply = rt.run(
                    conn.call(
                        "prefetch_objects", oids=hs, owner_addr=own_addr
                    )
                )
            except Exception as e:  # noqa: BLE001 - peer died: chunks
                logger.warning(     # stay local, head repair replaces
                    "erasure placement to %s failed: %r", peer, e
                )
                continue
            results = reply.get("results", {})
            ok = {h for h in hs if results.get(h)}
            if ok:
                confirmed += 1
            pushed[peer] = ok
        deletable: list[str] = []
        for h, tgts in assigned.items():
            landed = [
                t
                for t in tgts
                if t == own_addr or h in pushed.get(t, ())
            ]
            if landed and own_addr not in tgts:
                locations[h] = sorted(landed)
                deletable.append(h)
            else:
                locations[h] = sorted({own_addr, *landed})
        return confirmed, deletable

    # ------------------------------------------------------ remote tier
    def _remote_offload(
        self, shard_store, step, entries, parity, chunks, metrics,
        t_offloaded,
    ) -> dict | None:
        """Upload the committed manifest + chunks to the remote spill
        tier (CKPT_REMOTE_TIER), after in-cluster replication. Failure
        is ALERT + retry-next-save, never a save failure: the cluster
        copy committed, only cross-cluster durability lags."""
        from ray_tpu.checkpoint import remote as _remote

        tags = {"job": self.run}
        tier = _remote.get_tier()
        if tier is None:
            return None
        try:
            uploaded = 0
            for h in chunks:
                if tier.has_chunk(h):
                    continue
                data = shard_store.get_chunk(h)
                if data is None:
                    continue
                tier.put_chunk(h, data)
                uploaded += 1
            tier.put_manifest(
                self.run,
                int(step),
                self.rank,
                {
                    "run": self.run,
                    "step": int(step),
                    "rank": self.rank,
                    "world": self.world,
                    "entries": entries,
                    "parity": parity,
                    "metrics": metrics,
                    "ts": time.time(),
                },
            )
        except _remote.RemoteTierError as e:
            REMOTE_ERRORS.inc(1, tags=tags)
            REMOTE_ALERT.set(1.0, tags=tags)
            logger.warning(
                "remote tier offload failed for %s step %s: %s "
                "(saves continue in-cluster)", self.run, step, e,
            )
            return {"ok": False, "error": str(e)}
        lag = time.time() - t_offloaded
        REMOTE_LAG.set(lag, tags=tags)
        REMOTE_ALERT.set(0.0, tags=tags)
        return {"ok": True, "chunks_uploaded": uploaded, "lag_s": lag}

    # -------------------------------------------------------- replicate
    def _pick_peers(self, rt, own_addr: str) -> list[str]:
        """R-1 peer node addrs across DISTINCT slices: a replica on the
        same slice as another copy dies with it under whole-slice
        preemption, so the first R-1 picks cover R-1 different slices
        when the cluster has them (one peer per slice, round-robin),
        before doubling up within a slice; same-slice-as-us and
        draining nodes come last."""
        return self._peer_candidates(rt, own_addr)[
            : max(0, self.replication - 1)
        ]

    def _peer_candidates(self, rt, own_addr: str) -> list[str]:
        """Every peer node addr, ordered slice-diverse-first (one addr
        per slice per round), then same-slice/draining fallbacks, with a
        deterministic per-rank rotation."""
        try:
            status = rt.run(rt.core.head.call("cluster_status"))
        except Exception as e:  # noqa: BLE001 - degraded head: local-only
            logger.warning("checkpoint peer pick failed: %r", e)
            return []
        draining = set(status.get("draining") or {})
        nodes = status.get("nodes", {})
        own_slice = None
        for nid, n in nodes.items():
            if n.get("addr") == own_addr:
                own_slice = (n.get("labels") or {}).get("slice")
        # slice label (or per-node singleton domain) → fresh addrs
        by_slice: dict[str, list[str]] = {}
        fallback = []
        for nid, n in nodes.items():
            addr = n.get("addr")
            if not addr or addr == own_addr:
                continue
            labels = n.get("labels") or {}
            if nid in draining:
                fallback.append(addr)
            elif own_slice is not None and labels.get("slice") == own_slice:
                fallback.append(addr)
            else:
                domain = labels.get("slice") or f"node:{addr}"
                by_slice.setdefault(domain, []).append(addr)
        # Interleave one addr per slice per round: the first R-1 picks
        # maximize slice diversity by construction.
        fresh: list[str] = []
        rounds = [sorted(by_slice[d]) for d in sorted(by_slice)]
        while rounds:
            next_rounds = []
            for addrs in rounds:
                fresh.append(addrs.pop(0))
                if addrs:
                    next_rounds.append(addrs)
            rounds = next_rounds
        # Deterministic per-rank rotation spreads replica load across the
        # cluster instead of every rank hammering the same peer.
        candidates = fresh + sorted(fallback)
        if candidates:
            shift = self.rank % len(candidates)
            candidates = candidates[shift:] + candidates[:shift]
        return candidates

    def _replicate(
        self, rt, chunks: list[str], own_addr: str, locations: dict
    ) -> int:
        """Push every chunk of this checkpoint at R-1 peers (peers skip
        chunks they already hold, so dedup'd saves replicate for free).
        Returns the number of peer replicas confirmed."""
        if self.replication <= 1 or not chunks:
            return 0
        confirmed = 0
        for peer in self._pick_peers(rt, own_addr):
            try:
                conn = rt.run(rt.core._connect(peer))
                reply = rt.run(
                    conn.call(
                        "prefetch_objects",
                        oids=chunks,
                        owner_addr=own_addr,
                    )
                )
            except Exception as e:  # noqa: BLE001 - peer died: head repair
                logger.warning(            # re-replicates once it notices
                    "checkpoint replication to %s failed: %r", peer, e
                )
                continue
            results = reply.get("results", {})
            for h in chunks:
                if results.get(h):
                    locations.setdefault(h, []).append(peer)
            confirmed += 1
        return confirmed
