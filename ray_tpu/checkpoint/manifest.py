"""Checkpoint manifests: the leaf → chunk mapping and its assembly.

A checkpoint is a set of per-rank manifests committed to the head. Each
manifest entry describes one pytree leaf this rank owns: global shape,
dtype, and the shard windows it persisted (index ranges into the global
array plus the content hashes of the chunks holding that window's
bytes). The manifest is the ONLY record that a checkpoint exists —
chunks without a committed manifest are invisible garbage, which is what
makes a save that dies mid-write harmless (the previous manifest still
resolves, the orphan chunks get collected).

Ownership is ZeRO-flavored (arXiv:2004.13336): optimizer/parameter state
that is replicated across data-parallel workers is partitioned leaf-wise
round-robin by rank so each worker persists a disjoint 1/world of the
bytes with no gather; a leaf that is genuinely sharded across processes
(multi-host jax.Array) is instead persisted by every rank as its
addressable shard windows (replica 0 only), which is the same
no-gather property at sub-leaf granularity.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np


def _np_dtype(name: str) -> np.dtype:
    """dtype from its manifest name, covering the ml_dtypes extras
    (bfloat16 & friends) numpy alone can't parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def flatten_with_keys(tree: Any) -> list[tuple[str, Any]]:
    """(key, leaf) pairs in a stable, sorted order. The key is the jax
    path string — identical across processes for identical structures,
    which is what makes round-robin ownership a consistent partition."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = [(jax.tree_util.keystr(path), leaf) for path, leaf in leaves]
    out.sort(key=lambda kv: kv[0])
    return out


def _is_process_sharded(leaf: Any) -> bool:
    return getattr(leaf, "is_fully_addressable", True) is False


def owned_items(
    tree: Any,
    rank: int,
    world: int,
    local_prefixes: tuple[str, ...] = (),
) -> list[tuple[str, Any]]:
    """The (key, leaf) items THIS rank persists: its round-robin slice of
    the replicated leaves plus every process-sharded leaf (each process
    then persists only its addressable windows).

    ``local_prefixes`` marks subtrees that are ALREADY a disjoint
    per-rank shard (the ZeRO-sharded optimizer state, train/zero.py:
    each rank's tree holds only the leaves it owns): every present leaf
    under such a prefix is persisted unconditionally — round-robin
    re-partitioning a per-rank-distinct key set would be inconsistent
    across ranks. The head merges all ranks' entries by key, so the
    committed manifest carries the full sharded state with no gather."""
    items = flatten_with_keys(tree)
    # Round-robin indexes count only the replicated (non-local) leaves
    # so the partition stays consistent whatever each rank's local
    # shard happens to contain.
    out = []
    i = 0
    for key, leaf in items:
        if local_prefixes and any(
            key.startswith(p) for p in local_prefixes
        ):
            out.append((key, leaf))
            continue
        if _is_process_sharded(leaf) or i % max(1, world) == rank % max(
            1, world
        ):
            out.append((key, leaf))
        i += 1
    return out


def local_shards(leaf: Any) -> list[tuple[list | None, np.ndarray]]:
    """(index_spec, host_array) windows of this leaf owned by this
    process. index_spec is [[start, stop], ...] per dim (None = the whole
    array). jax.Arrays contribute their addressable shards (replica 0
    only — replicas would write identical chunks, wasted hashing);
    anything else is one full window."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        return [(None, np.asarray(leaf))]
    shape = leaf.shape
    out: list[tuple[list | None, np.ndarray]] = []
    for sh in shards:
        if getattr(sh, "replica_id", 0) != 0:
            continue
        spec: list | None = [
            [s.start or 0, s.stop if s.stop is not None else dim]
            for s, dim in zip(sh.index, shape)
        ]
        if all(a == 0 and b == dim for (a, b), dim in zip(spec, shape)):
            spec = None
        out.append((spec, np.asarray(sh.data)))
    if not out:
        # Every addressable shard was a replica>0 copy (possible on an
        # asymmetric mesh): fall back to the full array so the leaf is
        # never silently dropped from the checkpoint.
        out.append((None, np.asarray(leaf)))
    return out


def shard_shape(entry_shape: list, index: list | None) -> tuple:
    if index is None:
        return tuple(entry_shape)
    return tuple(b - a for a, b in index)


def assemble_leaf(
    key: str,
    shape: list,
    dtype: str,
    shards: list[dict],
    fetch_chunk: Callable[[str], bytes],
) -> np.ndarray:
    """Rebuild one leaf from its shard windows, pulling chunk bytes
    through ``fetch_chunk(hash)``. Works for any surviving-replica set:
    windows may come from different ranks' manifests."""
    dt = _np_dtype(dtype)
    if not shape:
        data = b"".join(fetch_chunk(h) for h in shards[0]["chunks"])
        return np.frombuffer(data, dtype=dt)[0].copy()
    out = np.empty(tuple(shape), dtype=dt)
    covered = 0
    for sh in shards:
        data = b"".join(fetch_chunk(h) for h in sh["chunks"])
        window = np.frombuffer(data, dtype=dt).reshape(
            shard_shape(shape, sh.get("index"))
        )
        if sh.get("index") is None:
            out[...] = window
        else:
            out[tuple(slice(a, b) for a, b in sh["index"])] = window
        covered += window.size
    if covered < int(np.prod(shape)):
        raise ValueError(
            f"checkpoint leaf {key}: shard windows cover {covered} of "
            f"{int(np.prod(shape))} elements — a rank's manifest is "
            "missing (incomplete checkpoint exposed?)"
        )
    return out


def entry_bytes(entry: dict) -> int:
    return sum(int(sh.get("nbytes", 0)) for sh in entry.get("shards", ()))


def manifest_chunks(entries: dict | list) -> set[str]:
    """Every chunk hash referenced by a manifest's entries (dict keyed by
    leaf or a plain list of entries)."""
    vals = entries.values() if isinstance(entries, dict) else entries
    out: set[str] = set()
    for e in vals:
        for sh in e.get("shards", ()):
            out.update(sh.get("chunks", ()))
    return out


# Parity groups ride the manifest next to the entries: each record is
# {"data": [hash...], "parity": [hash...], "lens": [int...]} — the k
# data members (in matrix-row order), the m parity chunk hashes, and
# the true byte length of each data member (parity is computed over
# zero-padded equal-width rows; lens trims the reconstruction).

def parity_chunks(parity: list | None) -> set[str]:
    """Every PARITY chunk hash recorded by a manifest's parity groups
    (the data members are already covered by manifest_chunks)."""
    out: set[str] = set()
    for g in parity or ():
        out.update(g.get("parity", ()))
    return out


def parity_group_index(parity: list | None) -> dict[str, dict]:
    """chunk hash → its parity-group record, for every member (data and
    parity) of every group. First group wins on (rare) dedup overlap."""
    out: dict[str, dict] = {}
    for g in parity or ():
        for h in list(g.get("data", ())) + list(g.get("parity", ())):
            out.setdefault(h, g)
    return out
