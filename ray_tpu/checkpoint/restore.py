"""Elastic resharded restore from the in-cluster shard store.

Restore resolves a committed manifest from the head, assembles each leaf
from whichever chunk replicas survive (local store first, then peer
nodes over the pipelined transfer path), and re-places the result onto
the CURRENT mesh via the ``shardings=`` pytree — so a run that saved
from N workers resumes on M (the elastic resume path) without any
shared filesystem.
"""

from __future__ import annotations

import asyncio
import logging

from ray_tpu.checkpoint import manifest as _manifest
from ray_tpu.checkpoint.saver import _runtime
from ray_tpu.checkpoint.store import ShardStore, parse_uri

logger = logging.getLogger("ray_tpu.checkpoint")

_PULL_WINDOW = 8  # concurrent chunk pulls per restore


def latest_step(run: str) -> int | None:
    """Newest COMPLETE checkpoint step for a run, or None."""
    rt = _runtime()
    reply = rt.run(rt.core.head.call("ckpt_list", run=run))
    steps = [
        c["step"]
        for c in reply.get("runs", {}).get(run, [])
        if c.get("complete")
    ]
    return max(steps) if steps else None


def list_checkpoints(run: str | None = None) -> dict:
    rt = _runtime()
    return rt.run(rt.core.head.call("ckpt_list", run=run))


async def _fetch_chunks(
    rt, hashes: list[str], locations: dict[str, list[str]]
) -> dict[str, bytes]:
    """Resolve chunk bytes: local store, then surviving peer replicas."""
    from ray_tpu.exceptions import ObjectLostError
    from ray_tpu.runtime import transfer

    shard_store = ShardStore(rt.core.store)
    out: dict[str, bytes] = {}
    remote: list[str] = []
    for h in hashes:
        data = shard_store.get_chunk(h)
        if data is not None:
            out[h] = data
        else:
            remote.append(h)
    if not remote:
        return out
    conns: dict[str, object] = {}
    for addr in {a for h in remote for a in locations.get(h, ())}:
        if addr == rt.core.node_addr:
            continue
        try:
            conns[addr] = await rt.core._connect(addr)
        except Exception as e:  # noqa: BLE001 - dead holder: use the rest
            logger.debug("checkpoint holder %s unreachable: %r", addr, e)
    sem = asyncio.Semaphore(_PULL_WINDOW)

    async def pull(h: str):
        srcs = [conns[a] for a in locations.get(h, ()) if a in conns]
        if not srcs:
            raise ObjectLostError(
                f"checkpoint chunk {h[:12]}…: no surviving replica"
            )
        async with sem:
            inband, _buffers = await transfer.pull_object(h, srcs)
        out[h] = inband
        # Cache locally: a retry attempt on this node restores from shm,
        # and this node becomes one more serving replica for peers.
        shard_store.put_chunk(h, inband)

    await asyncio.gather(*(pull(h) for h in remote))
    return out


def restore(
    run: str,
    step: int | None = None,
    *,
    target=None,
    shardings=None,
    keys=None,
):
    """Restore a committed checkpoint. ``target`` (pytree of arrays or
    anything with shape/dtype) pins structure; ``shardings`` (matching
    pytree) places each leaf on the current mesh — pass the NEW mesh's
    shardings to resume elastically on a different layout. Without
    ``target`` returns ``{leaf_key: np.ndarray}``; ``keys`` narrows
    that form to a subset of leaves.

    Chunk pulls are scoped to the leaves actually assembled (the
    ``target``'s keys or the ``keys`` filter) — a ZeRO-sharded restore
    (train/zero.py) therefore pulls only this rank's shard of the
    optimizer state, never materializing the full fp32 state on any
    one chip."""
    rt = _runtime()
    reply = rt.run(rt.core.head.call("ckpt_manifest", run=run, step=step))
    if not reply.get("ok"):
        raise FileNotFoundError(
            f"no complete checkpoint for run {run!r}"
            + (f" step {step}" if step is not None else "")
            + f": {reply.get('error', '')}"
        )
    entries: dict[str, dict] = reply["entries"]
    locations: dict[str, list[str]] = reply.get("locations", {})

    if target is not None:
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        wanted = [jax.tree_util.keystr(path) for path, _leaf in flat]
    elif keys is not None:
        wanted = sorted(keys)
        missing = [k for k in wanted if k not in entries]
        if missing:
            raise KeyError(
                f"checkpoint for run {run!r} has no leaves "
                f"{missing[:4]}; saved leaves: {sorted(entries)[:8]}…"
            )
    else:
        wanted = sorted(entries)
    needed = {k: entries[k] for k in wanted if k in entries}
    hashes = sorted(_manifest.manifest_chunks(needed))
    chunks = rt.run(_fetch_chunks(rt, hashes, locations))

    def assemble(key: str):
        e = entries[key]
        return _manifest.assemble_leaf(
            key, e["shape"], e["dtype"], e["shards"], chunks.__getitem__
        )

    if target is None:
        return {key: assemble(key) for key in wanted}

    values = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key not in entries:
            raise KeyError(
                f"checkpoint for run {run!r} has no leaf {key}; "
                f"saved leaves: {sorted(entries)[:8]}…"
            )
        arr = assemble(key)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"checkpoint leaf {key}: saved shape {tuple(arr.shape)} "
                f"!= target shape {tuple(leaf.shape)}"
            )
        values.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, values)
    if shardings is not None:
        state = jax.device_put(state, shardings)
    return state


def restore_uri(uri: str, *, target=None, shardings=None, keys=None):
    run, step = parse_uri(uri)
    return restore(run, step, target=target, shardings=shardings, keys=keys)
