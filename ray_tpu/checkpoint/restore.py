"""Elastic resharded restore from the in-cluster shard store.

Restore resolves a committed manifest from the head, assembles each leaf
from whichever chunk replicas survive, and re-places the result onto
the CURRENT mesh via the ``shardings=`` pytree — so a run that saved
from N workers resumes on M (the elastic resume path) without any
shared filesystem.

The resolution ladder per chunk, cheapest first:

1. ``have=`` fingerprint — the live tree's bytes hashed through the
   same chunker (differential restore: a warm restart pulls ~0 bytes),
2. local shard store,
3. surviving peer replicas (pipelined transfer path),
4. erasure reconstruction from ≥k surviving group members,
5. the remote spill tier (CKPT_REMOTE_TIER),

and only then ``ObjectLostError``. Chunks gained along the way are
cached locally and reported to the head's location table in one batch,
so the restoring node immediately serves peers and GC sees the replica.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from ray_tpu.checkpoint import erasure as _erasure
from ray_tpu.checkpoint import manifest as _manifest
from ray_tpu.checkpoint.saver import _runtime
from ray_tpu.checkpoint.store import (
    ShardStore,
    chunk_hash,
    default_chunk_bytes,
    parse_uri,
)

logger = logging.getLogger("ray_tpu.checkpoint")

_PULL_WINDOW = 8  # concurrent chunk pulls per restore

# Stats of the LAST restore in this process (tests pin differential
# restore's ~0-pull property on these; dashboards read them too).
last_restore_stats: dict = {}


def latest_step(run: str) -> int | None:
    """Newest COMPLETE checkpoint step for a run, or None."""
    rt = _runtime()
    reply = rt.run(rt.core.head.call("ckpt_list", run=run))
    steps = [
        c["step"]
        for c in reply.get("runs", {}).get(run, [])
        if c.get("complete")
    ]
    return max(steps) if steps else None


def list_checkpoints(run: str | None = None) -> dict:
    rt = _runtime()
    return rt.run(rt.core.head.call("ckpt_list", run=run))


async def _fetch_chunks(
    rt,
    hashes: list[str],
    locations: dict[str, list[str]],
    parity: list | None = None,
    known: dict[str, bytes] | None = None,
    stats: dict | None = None,
) -> dict[str, bytes]:
    """Resolve chunk bytes down the ladder: ``known`` (differential
    fingerprint hits) → local store → peer replicas → erasure
    reconstruction → remote tier → ObjectLostError."""
    from ray_tpu.exceptions import ObjectLostError
    from ray_tpu.runtime import transfer

    stats = stats if stats is not None else {}
    for k in (
        "total", "have_hits", "local", "pulled",
        "reconstructed", "remote_tier",
    ):
        stats.setdefault(k, 0)
    stats["total"] += len(hashes)
    shard_store = ShardStore(rt.core.store)
    out: dict[str, bytes] = {}
    missing: list[str] = []
    for h in hashes:
        if known is not None and h in known:
            out[h] = known[h]
            stats["have_hits"] += 1
            continue
        data = shard_store.get_chunk(h)
        if data is not None:
            out[h] = data
            stats["local"] += 1
        else:
            missing.append(h)
    gained: set[str] = set()
    if missing:
        conns: dict[str, object] = {}

        async def connect(addr: str):
            if addr in conns or addr == rt.core.node_addr:
                return conns.get(addr)
            try:
                conns[addr] = await rt.core._connect(addr)
            except Exception as e:  # noqa: BLE001 - dead holder: rest
                logger.debug(
                    "checkpoint holder %s unreachable: %r", addr, e
                )
                conns[addr] = None
            return conns[addr]

        for addr in {a for h in missing for a in locations.get(h, ())}:
            await connect(addr)
        sem = asyncio.Semaphore(_PULL_WINDOW)
        failed: list[str] = []

        async def pull(h: str):
            srcs = [
                conns[a]
                for a in locations.get(h, ())
                if conns.get(a) is not None
            ]
            if not srcs:
                failed.append(h)
                return
            try:
                async with sem:
                    inband, _buffers = await transfer.pull_object(h, srcs)
            except Exception as e:  # noqa: BLE001 - replicas died
                logger.debug("chunk pull %s failed: %r", h[:12], e)
                failed.append(h)
                return
            if chunk_hash(inband) != h:
                # A peer served corrupt bytes — same treatment as a
                # local hash mismatch: this replica does not count.
                logger.warning(
                    "chunk %s pulled from peer failed content-hash "
                    "check", h[:12],
                )
                failed.append(h)
                return
            out[h] = inband
            gained.add(h)
            stats["pulled"] += 1

        await asyncio.gather(*(pull(h) for h in missing))

        if failed and parity:
            group_of = _manifest.parity_group_index(parity)
            for h in list(failed):
                if h not in group_of:
                    continue
                data = await _reconstruct_chunk(
                    rt, h, group_of[h], out, locations, connect, sem
                )
                if data is not None:
                    out[h] = data
                    gained.add(h)
                    failed.remove(h)
                    stats["reconstructed"] += 1

        if failed:
            from ray_tpu.checkpoint import remote as _remote

            tier = _remote.get_tier()
            for h in list(failed):
                if tier is None:
                    break
                # RemoteTierError propagates: a tier outage while chunks
                # are otherwise lost IS the typed, deadline-bounded
                # failure the caller should see — never a hang.
                data = tier.get_chunk(h)
                if data is not None and chunk_hash(data) == h:
                    out[h] = data
                    gained.add(h)
                    failed.remove(h)
                    stats["remote_tier"] += 1

        if failed:
            raise ObjectLostError(
                f"checkpoint chunk {failed[0][:12]}…: no surviving "
                f"replica ({len(failed)} chunks unrecoverable; tried "
                "peers, parity, remote tier)"
            )
    if gained:
        # Cache locally: a retry attempt on this node restores from shm,
        # and this node becomes one more serving replica for peers —
        # which peers can only FIND if the head's location table knows
        # (one batched report; GC also needs it to collect this copy).
        for h in gained:
            shard_store.put_chunk(h, out[h])
        try:
            await rt.core.head.call(
                "ckpt_locations_add",
                addr=rt.core.node_addr or rt.core.addr,
                chunks=sorted(gained),
            )
        except Exception as e:  # noqa: BLE001 - head mid-failover:
            logger.debug(        # verify/repair probes catch up later
                "ckpt location report failed: %r", e
            )
    return out


async def _reconstruct_chunk(
    rt, h, group, out, locations, connect, sem
):
    """Erasure path: gather ≥k surviving members of ``h``'s parity group
    (preferring bytes already fetched), decode, verify by content hash.
    Returns None when not enough members survive."""
    from ray_tpu.runtime import transfer

    members = list(group.get("data", ())) + list(group.get("parity", ()))
    k = len(group.get("data", ()))
    m = len(group.get("parity", ()))
    shard_store = ShardStore(rt.core.store)
    present: dict[int, bytes] = {}
    for idx, mh in enumerate(members):
        if len(present) >= k:
            break
        if mh == h:
            continue
        data = out.get(mh)
        if data is None:
            data = shard_store.get_chunk(mh)
        if data is None:
            for addr in locations.get(mh, ()):
                conn = await connect(addr)
                if conn is None:
                    continue
                try:
                    async with sem:
                        data, _buffers = await transfer.pull_object(
                            mh, [conn]
                        )
                except Exception as e:  # noqa: BLE001 - try next holder
                    logger.debug(
                        "group-member pull of %s from %s failed: %r",
                        mh[:12], addr, e,
                    )
                    data = None
                    continue
                if chunk_hash(data) == mh:
                    break
                data = None
        if data is not None:
            present[idx] = data
    if len(present) < k:
        logger.debug(
            "chunk %s: only %d/%d group members survive, cannot "
            "reconstruct", h[:12], len(present), k,
        )
        return None
    want = group["data"].index(h)
    try:
        data = _erasure.reconstruct(
            k, m, present, [want], group.get("lens")
        )[want]
    except Exception as e:  # noqa: BLE001 - singular/garbage survivors
        logger.warning("chunk %s reconstruction failed: %r", h[:12], e)
        return None
    if chunk_hash(data) != h:
        logger.warning(
            "chunk %s reconstruction produced wrong bytes (corrupt "
            "survivor?)", h[:12],
        )
        return None
    logger.info(
        "reconstructed checkpoint chunk %s from %d surviving group "
        "members", h[:12], len(present),
    )
    return data


def _fingerprint_have(have, needed: dict) -> dict[str, bytes]:
    """Differential restore: run the LIVE tree's bytes through the same
    chunker and keep pieces whose hashes match the manifest — those
    chunks never leave this host. Any layout/shape/chunk-size mismatch
    just means fewer hits, never a wrong restore (assembly only uses
    bytes that hash to the manifest's content address)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(have)
    live = {
        jax.tree_util.keystr(path): leaf for path, leaf in flat
    }
    n = default_chunk_bytes()
    known: dict[str, bytes] = {}
    for key, entry in needed.items():
        leaf = live.get(key)
        if leaf is None:
            continue
        try:
            arr = np.asarray(leaf)
        except Exception as e:  # noqa: BLE001 - non-addressable jax.Array
            logger.debug(
                "have-fingerprint skipping leaf %s: %r", key, e
            )
            continue
        if tuple(arr.shape) != tuple(entry["shape"]):
            continue
        for sh in entry.get("shards", ()):
            index = sh.get("index")
            window = (
                arr
                if index is None
                else arr[tuple(slice(a, b) for a, b in index)]
            )
            flatb = np.ascontiguousarray(window).reshape(-1).view(np.uint8)
            mv = memoryview(flatb)
            want = sh.get("chunks", ())
            for i, off in enumerate(range(0, max(1, len(mv)), n)):
                if i >= len(want):
                    break
                piece = bytes(mv[off : off + n])
                if chunk_hash(piece) == want[i]:
                    known[want[i]] = piece
    return known


def restore(
    run: str,
    step: int | None = None,
    *,
    target=None,
    shardings=None,
    keys=None,
    have=None,
):
    """Restore a committed checkpoint. ``target`` (pytree of arrays or
    anything with shape/dtype) pins structure; ``shardings`` (matching
    pytree) places each leaf on the current mesh — pass the NEW mesh's
    shardings to resume elastically on a different layout. Without
    ``target`` returns ``{leaf_key: np.ndarray}``; ``keys`` narrows
    that form to a subset of leaves.

    ``have=`` is the differential-restore hook: pass the LIVE state
    tree (e.g. the one still on device after a mid-run crash of a
    different worker) and its bytes are fingerprinted through the
    chunker — chunks whose content already matches the manifest are
    never pulled, so a warm restart moves ~0 bytes
    (``last_restore_stats`` records the split).

    Chunk pulls are scoped to the leaves actually assembled (the
    ``target``'s keys or the ``keys`` filter) — a ZeRO-sharded restore
    (train/zero.py) therefore pulls only this rank's shard of the
    optimizer state, never materializing the full fp32 state on any
    one chip."""
    global last_restore_stats
    rt = _runtime()
    reply = rt.run(rt.core.head.call("ckpt_manifest", run=run, step=step))
    if not reply.get("ok"):
        raise FileNotFoundError(
            f"no complete checkpoint for run {run!r}"
            + (f" step {step}" if step is not None else "")
            + f": {reply.get('error', '')}"
        )
    entries: dict[str, dict] = reply["entries"]
    locations: dict[str, list[str]] = reply.get("locations", {})
    parity: list = reply.get("parity", [])

    if target is not None:
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(target)
        wanted = [jax.tree_util.keystr(path) for path, _leaf in flat]
    elif keys is not None:
        wanted = sorted(keys)
        missing = [k for k in wanted if k not in entries]
        if missing:
            raise KeyError(
                f"checkpoint for run {run!r} has no leaves "
                f"{missing[:4]}; saved leaves: {sorted(entries)[:8]}…"
            )
    else:
        wanted = sorted(entries)
    needed = {k: entries[k] for k in wanted if k in entries}
    hashes = sorted(_manifest.manifest_chunks(needed))
    known = _fingerprint_have(have, needed) if have is not None else None
    stats: dict = {"run": run, "step": reply.get("step")}
    chunks = rt.run(
        _fetch_chunks(
            rt, hashes, locations, parity=parity, known=known, stats=stats
        )
    )
    last_restore_stats = stats

    def assemble(key: str):
        e = entries[key]
        return _manifest.assemble_leaf(
            key, e["shape"], e["dtype"], e["shards"], chunks.__getitem__
        )

    if target is None:
        return {key: assemble(key) for key in wanted}

    values = []
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        if key not in entries:
            raise KeyError(
                f"checkpoint for run {run!r} has no leaf {key}; "
                f"saved leaves: {sorted(entries)[:8]}…"
            )
        arr = assemble(key)
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"checkpoint leaf {key}: saved shape {tuple(arr.shape)} "
                f"!= target shape {tuple(leaf.shape)}"
            )
        values.append(arr)
    state = jax.tree_util.tree_unflatten(treedef, values)
    if shardings is not None:
        state = jax.device_put(state, shardings)
    return state


def restore_uri(uri: str, *, target=None, shardings=None, keys=None):
    run, step = parse_uri(uri)
    return restore(run, step, target=target, shardings=shardings, keys=keys)
