"""Checkpoint lineage fork — the PBT exploit primitive.

A fork re-commits a complete checkpoint's per-rank manifests under a
new run name on the head (``ckpt_fork`` RPC). Chunks are
content-addressed, so the fork moves ZERO bulk bytes: both runs'
manifests reference the same sha256 chunk hashes, the location table
already covers them, and the GC refcount protects them as long as
either lineage retains the step. A PBT exploit is therefore "copy the
winner's manifest, perturb the hyperparameters" — cost independent of
model size.

``fork_shares_chunks`` is the dedup assertion the bench and tests pin:
it verifies the forked manifest's chunk set is EXACTLY the source's
(ratio 1.0 shared, 0 new).
"""

from __future__ import annotations

import ray_tpu


def fork(run: str, new_run: str, step: int | None = None) -> dict:
    """Fork ``run``'s newest complete checkpoint (or ``step``) into
    ``new_run``. Returns the head's reply: ``{"ok", "run", "step",
    "ranks", "chunks", "new_bytes"}`` — ``new_bytes`` is 0 by
    construction. Raises ValueError when the source has no complete
    checkpoint."""
    rt = ray_tpu.api._runtime
    reply = rt.run(
        rt.core.head.call("ckpt_fork", run=run, new_run=new_run, step=step)
    )
    if not reply.get("ok"):
        raise ValueError(reply.get("error", "checkpoint fork failed"))
    return reply


def _manifest_chunk_set(run: str, step: int) -> set[str]:
    from ray_tpu.checkpoint.manifest import manifest_chunks

    rt = ray_tpu.api._runtime
    reply = rt.run(rt.core.head.call("ckpt_manifest", run=run, step=step))
    return manifest_chunks(reply.get("entries") or {})


def fork_shares_chunks(run: str, new_run: str, step: int) -> dict:
    """Dedup accounting for a completed fork: compares the two runs'
    manifests at ``step``. Returns ``{"src_chunks", "dst_chunks",
    "shared", "new_chunks", "dedup_ratio"}`` where ``dedup_ratio`` is
    shared/dst (1.0 = the fork introduced nothing)."""
    src = _manifest_chunk_set(run, step)
    dst = _manifest_chunk_set(new_run, step)
    shared = src & dst
    return {
        "src_chunks": len(src),
        "dst_chunks": len(dst),
        "shared": len(shared),
        "new_chunks": len(dst - src),
        "dedup_ratio": (len(shared) / len(dst)) if dst else 1.0,
    }
