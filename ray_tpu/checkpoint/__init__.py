"""Async distributed checkpoint subsystem.

Four pieces (see README "Checkpointing"):

- **Async snapshot-offload** (`AsyncCheckpointer`): ``save()`` pays only
  the device→host copy, a background thread persists + replicates +
  commits; ``wait()`` is the barrier.
- **Content-addressed shard store** (`store.ShardStore`): pytree leaves
  land as sha256-keyed chunks in the node object store, deduplicating
  unchanged state between consecutive checkpoints.
- **Peer replication**: each chunk is replicated to R-1 peer nodes over
  the object-transfer path; the head journals manifests + replica
  locations and a repair loop re-replicates on node death/drain.
- **Elastic resharded restore** (`restore` / `restore_uri`): leaves are
  assembled from surviving replicas and re-placed onto the current mesh
  via ``shardings=`` — no shared filesystem required.
"""

from ray_tpu.checkpoint.fork import fork, fork_shares_chunks
from ray_tpu.checkpoint.restore import (
    latest_step,
    list_checkpoints,
    restore,
    restore_uri,
)
from ray_tpu.checkpoint.saver import (
    AsyncCheckpointer,
    take_step_stall_seconds,
    wait_pending,
)
from ray_tpu.checkpoint.store import (
    CKPT_URI_PREFIX,
    ShardStore,
    is_ckpt_uri,
    make_uri,
    parse_uri,
)

__all__ = [
    "AsyncCheckpointer",
    "CKPT_URI_PREFIX",
    "ShardStore",
    "fork",
    "fork_shares_chunks",
    "is_ckpt_uri",
    "latest_step",
    "list_checkpoints",
    "make_uri",
    "parse_uri",
    "restore",
    "restore_uri",
    "take_step_stall_seconds",
    "wait_pending",
]
