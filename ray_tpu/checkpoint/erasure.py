"""Chunk-level k+m erasure coding for checkpoint shards.

Replication keeps whole copies (2x bytes for one-failure tolerance);
erasure coding stores k data chunks plus m parity chunks ((k+m)/k x
bytes, any-m-failure tolerance — k=4,m=2 survives two lost nodes at
1.5x). The codec is systematic: data chunks are stored verbatim (the
content-addressed dedup ledger is untouched) and only the parity chunks
are computed, so the read path pays nothing while every group member
survives.

Arithmetic is GF(2^8): addition IS xor, multiplication goes through
log/exp tables and vectorizes with ``np.take`` over a per-coefficient
256-entry product table — pure python/numpy, no native codec
dependency. Parity rows come from a Cauchy matrix (every square
submatrix invertible), so reconstruction of any <= m missing members is
a small k x k solve regardless of which members died. With m=1 the
single parity row degenerates to the plain xor of the data chunks.

Chunks in a group may have different true lengths (the tail chunk of a
shard is short); encoding zero-pads to the group max and the manifest
records true lengths so reconstruction can trim.
"""

from __future__ import annotations

import numpy as np

# GF(2^8) with the usual primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d).
_GF_EXP = np.zeros(512, dtype=np.uint8)
_GF_LOG = np.zeros(256, dtype=np.int32)


def _build_tables() -> None:
    x = 1
    for i in range(255):
        _GF_EXP[i] = x
        _GF_LOG[x] = i
        x <<= 1
        if x & 0x100:
            x ^= 0x11D
    # Doubled table lets gf_mul index log(a)+log(b) without a mod.
    _GF_EXP[255:510] = _GF_EXP[0:255]


_build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(_GF_EXP[_GF_LOG[a] + _GF_LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf(256) inverse of 0")
    return int(_GF_EXP[255 - _GF_LOG[a]])


def _mul_table(c: int) -> np.ndarray:
    """256-entry table t where t[v] = c*v, for vectorized row scaling."""
    if c == 0:
        return np.zeros(256, dtype=np.uint8)
    if c == 1:
        return np.arange(256, dtype=np.uint8)
    t = _GF_EXP[(_GF_LOG[1:] + _GF_LOG[c]) % 255]
    return np.concatenate(([np.uint8(0)], t))


def _scale_xor(acc: np.ndarray, c: int, vec: np.ndarray) -> None:
    """acc ^= c * vec (in place), vectorized over bytes."""
    if c == 0:
        return
    if c == 1:
        np.bitwise_xor(acc, vec, out=acc)
        return
    np.bitwise_xor(acc, np.take(_mul_table(c), vec), out=acc)


def parity_rows(k: int, m: int) -> list[list[int]]:
    """Cauchy parity matrix rows: row j, col i = 1/(x_j + y_i) with
    x_j = j and y_i = m + i (all 2^8 elements distinct for k+m <= 256).
    Every square submatrix of a Cauchy matrix is invertible, so the
    systematic code [I; C] is MDS for any loss pattern."""
    if k < 1 or m < 0 or k + m > 256:
        raise ValueError(f"unsupported erasure geometry k={k} m={m}")
    return [[gf_inv(j ^ (m + i)) for i in range(k)] for j in range(m)]


def parse_spec(spec: str) -> tuple[int, int] | None:
    """Parse CKPT_ERASURE="k,m". Empty/0 disables; returns (k, m)."""
    spec = (spec or "").strip()
    if not spec or spec in ("0", "off", "none"):
        return None
    try:
        k_s, _, m_s = spec.partition(",")
        k, m = int(k_s), int(m_s or 1)
    except ValueError:
        raise ValueError(f"CKPT_ERASURE must be 'k,m', got {spec!r}")
    if k < 2 or m < 1 or k + m > 256:
        raise ValueError(f"CKPT_ERASURE out of range: k={k} m={m}")
    return k, m


def _as_padded(datas: list[bytes], width: int) -> list[np.ndarray]:
    out = []
    for d in datas:
        a = np.frombuffer(d, dtype=np.uint8)
        if len(a) < width:
            a = np.concatenate([a, np.zeros(width - len(a), dtype=np.uint8)])
        out.append(a)
    return out


def encode(datas: list[bytes], m: int) -> list[bytes]:
    """Compute m parity chunks over k data chunks (zero-padded to the
    longest member). Row 0 of the Cauchy matrix is not all-ones, but for
    m=1 the code is still a single-erasure parity; callers never need to
    care which matrix generated the bytes."""
    k = len(datas)
    rows = parity_rows(k, m)
    width = max((len(d) for d in datas), default=0)
    padded = _as_padded(datas, width)
    out = []
    for j in range(m):
        acc = np.zeros(width, dtype=np.uint8)
        for i in range(k):
            _scale_xor(acc, rows[j][i], padded[i])
        out.append(acc.tobytes())
    return out


def _solve(mat: list[list[int]], rhs: list[np.ndarray]) -> list[np.ndarray]:
    """Gauss-Jordan over GF(2^8); mat is k x k of ints, rhs k byte
    vectors. k is small (<= 16 in practice) so the O(k^3) python loop is
    nothing next to the byte work, which stays vectorized."""
    k = len(mat)
    a = [row[:] for row in mat]
    b = [v.copy() for v in rhs]
    for col in range(k):
        piv = next((r for r in range(col, k) if a[r][col]), None)
        if piv is None:
            raise ValueError("singular erasure matrix (bad survivor set)")
        if piv != col:
            a[col], a[piv] = a[piv], a[col]
            b[col], b[piv] = b[piv], b[col]
        inv = gf_inv(a[col][col])
        a[col] = [gf_mul(inv, v) for v in a[col]]
        b[col] = np.take(_mul_table(inv), b[col])
        for r in range(k):
            if r != col and a[r][col]:
                c = a[r][col]
                a[r] = [x ^ gf_mul(c, y) for x, y in zip(a[r], a[col])]
                _scale_xor(b[r], c, b[col])
    return b


def reconstruct(
    k: int,
    m: int,
    present: dict[int, bytes],
    want: list[int],
    lens: list[int] | None = None,
) -> dict[int, bytes]:
    """Recover missing DATA members from any k surviving members.

    ``present`` maps member index -> bytes, where indices 0..k-1 are
    data chunks and k..k+m-1 are parity chunks. ``want`` lists the data
    indices to recover. ``lens`` (optional) gives true data lengths for
    trimming the zero padding.
    """
    if len(present) < k:
        raise ValueError(
            f"need {k} survivors to reconstruct, have {len(present)}"
        )
    rows = parity_rows(k, m)
    use = sorted(present)[:k]
    width = max(len(present[i]) for i in use)
    vecs = _as_padded([present[i] for i in use], width)
    mat = []
    for idx in use:
        if idx < k:
            mat.append([1 if c == idx else 0 for c in range(k)])
        else:
            mat.append(rows[idx - k])
    datas = _solve(mat, vecs)
    out = {}
    for w in want:
        if not 0 <= w < k:
            raise ValueError(f"can only reconstruct data members, got {w}")
        raw = datas[w].tobytes()
        if lens is not None:
            raw = raw[: lens[w]]
        out[w] = raw
    return out


def recover_member(
    k: int,
    m: int,
    present: dict[int, bytes],
    member: int,
    lens: list[int] | None = None,
) -> bytes:
    """Recover ANY single lost member — data (index < k) or parity
    (index >= k) — from >= k survivors. A lost parity member is
    recovered by first solving for any missing data rows, then
    re-encoding its matrix row over the full data set."""
    if member < k:
        return reconstruct(k, m, present, [member], lens)[member]
    if not k <= member < k + m:
        raise ValueError(f"member {member} out of range for k={k} m={m}")
    missing = [i for i in range(k) if i not in present]
    rec = reconstruct(k, m, present, missing, None) if missing else {}
    rows = [bytes(present.get(i, rec.get(i))) for i in range(k)]
    return encode(rows, m)[member - k]


def plan_groups(hashes: list[str], k: int) -> list[list[str]]:
    """Split an ordered chunk list into parity groups of k data members.
    The tail group may be smaller than k (it still gets m parity chunks
    — slightly richer protection for slightly worse ratio on the tail)."""
    seen: set[str] = set()
    uniq = [h for h in hashes if not (h in seen or seen.add(h))]
    return [uniq[i : i + k] for i in range(0, len(uniq), k)]
