"""GCP/GKE TPU node provider: real REST calls behind a transport seam.

Reference shape: python/ray/autoscaler/_private/gcp/node_provider.py +
node.py — a GCPResource per API (compute/tpu) doing REST calls through
an authorized http object, with operation polling and label-based
cluster membership; tpu_command_runner.py handles multi-host slices.
TPU-native differences here:

- The scaling unit is a SLICE, never a VM. Two provisioning paths:
  * ``queued_resource`` (Cloud TPU API v2 ``queuedResources``) — the
    modern way to obtain slices, including spot/reserved queueing
    (reference node.py:785 uses the older projects.locations.nodes).
  * ``node_pool`` (GKE ``nodePools:setSize``) — TPU slice node pools
    in a GKE cluster; one size increment = one slice replica.
- Every created queued resource is labeled with the ray_tpu cluster
  name and node type, so membership listing is a label filter, and the
  provider id is stamped into instance metadata — the node daemon's
  detect_labels probes GCE metadata (node.py _gce_metadata_labels) and
  registers it as a node label, which runtime_node_id matches against
  the head's node table. node_pool mode cannot stamp per-increment
  metadata (setSize is anonymous): inject ``runtime_lookup`` (e.g.
  keyed on GKE node labels) or rely on the autoscaler's boot-grace
  accounting.

Auth rides a bearer token: ``GOOGLE_OAUTH_ACCESS_TOKEN`` env when set
(CI/dev), else the GCE metadata server (in-cluster). CI never talks to
Google: tests drive the provider through RecordedTransport fixtures.
"""

from __future__ import annotations

import json
import time
import urllib.request
import uuid
from typing import Any, Callable

from ray_tpu.autoscaler.providers import NodeProvider

_TPU_API = "https://tpu.googleapis.com/v2"
_GKE_API = "https://container.googleapis.com/v1"
_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)


class GcpHttpError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:500]}")
        self.status = status


class GcpTransport:
    """Minimal authorized REST transport (the AuthorizedHttp analogue,
    reference node.py:240)."""

    def __init__(
        self,
        token_provider: Callable[[], "str | tuple[str, float]"] | None = None,
    ):
        self._token_provider = token_provider or self._default_token
        self._token: str | None = None
        self._token_expiry = 0.0

    @staticmethod
    def _default_token() -> tuple[str, float]:
        import os

        env = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env:
            return env, 600.0
        req = urllib.request.Request(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        return payload["access_token"], float(payload.get("expires_in", 600))

    def _bearer(self) -> str:
        if self._token is None or time.time() > self._token_expiry:
            got = self._token_provider()
            # Providers may return a bare token or (token, expires_in).
            token, expires_in = got if isinstance(got, tuple) else (got, 600.0)
            self._token = token
            # Honor the server's actual lifetime, minus a safety margin so
            # a token fetched near expiry isn't cached past its death.
            self._token_expiry = time.time() + max(expires_in - 60.0, 10.0)
        return self._token

    def _invalidate_token(self) -> None:
        self._token = None
        self._token_expiry = 0.0

    def request(
        self, method: str, url: str, body: dict | None = None
    ) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        for attempt in (0, 1):
            req = urllib.request.Request(
                url,
                data=data,
                method=method,
                headers={
                    "Authorization": f"Bearer {self._bearer()}",
                    "Content-Type": "application/json",
                },
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                break
            except urllib.error.HTTPError as e:
                if e.code == 401 and attempt == 0:
                    # Stale cached token: drop it and retry once fresh.
                    self._invalidate_token()
                    continue
                raise GcpHttpError(e.code, e.read().decode("utf-8", "replace"))
        return json.loads(payload) if payload else {}


class RecordedTransport:
    """Replays a recorded call script (CI has zero egress). Each entry:
    {"method", "url", "response", optional "body_contains"}. Calls must
    arrive in order; mismatches raise with the diff."""

    def __init__(self, script: list[dict]):
        self.script = list(script)
        self.calls: list[tuple] = []
        self._i = 0

    def request(
        self, method: str, url: str, body: dict | None = None
    ) -> dict:
        self.calls.append((method, url, body))
        if self._i >= len(self.script):
            raise AssertionError(
                f"unexpected extra call #{self._i}: {method} {url}"
            )
        expect = self.script[self._i]
        self._i += 1
        if expect["method"] != method or expect["url"] != url:
            raise AssertionError(
                f"call #{self._i - 1}: got {method} {url}, expected "
                f"{expect['method']} {expect['url']}"
            )
        for fragment in expect.get("body_contains", ()):
            if fragment not in json.dumps(body or {}):
                raise AssertionError(
                    f"call #{self._i - 1}: body missing {fragment!r}: "
                    f"{body}"
                )
        if "error_status" in expect:
            raise GcpHttpError(expect["error_status"], expect.get(
                "error_body", ""
            ))
        return expect["response"]

    def assert_done(self):
        if self._i != len(self.script):
            raise AssertionError(
                f"{len(self.script) - self._i} scripted calls never "
                f"made: {self.script[self._i:]}"
            )


class GkeTpuNodeProvider(NodeProvider):
    """TPU-slice provider over the GKE / Cloud TPU REST surface.

    ``node_pools`` maps node_type → pool spec:

        {"mode": "queued_resource", "accelerator": "v5litepod-8",
         "runtime_version": "v2-alpha-tpuv5-lite", "spot": False}
      or
        {"mode": "node_pool", "pool": "tpu-v5e-8"}

    Slice semantics: one create_node == one whole slice (all its hosts
    share ICI and live or die together, reference util/tpu.py
    SlicePlacementGroup); terminate reaps the slice as a unit.
    """

    def __init__(
        self,
        project: str,
        location: str,
        cluster: str,
        node_pools: dict[str, dict],
        transport=None,
        runtime_lookup: Callable[[str], str | None] | None = None,
        operation_poll_s: float = 2.0,
    ):
        self.project = project
        self.location = location
        self.cluster = cluster
        self.node_pools = node_pools
        self.http = transport or GcpTransport()
        self._runtime_lookup = runtime_lookup
        self._poll_s = operation_poll_s
        # provider_node_id → node_type cache of our own creations; the
        # authoritative list always comes from the API
        # (non_terminated_nodes), so a restarted provider process
        # re-discovers existing slices instead of leaking them.
        self._nodes: dict[str, str] = {}
        # pool name → node_type reverse map for node_pool-mode ids
        # ("<pool>#<i>"), stable across provider restarts.
        self._pool_types = {
            spec["pool"]: nt
            for nt, spec in node_pools.items()
            if spec.get("mode") == "node_pool"
        }

    # ------------------------------------------------------------ paths
    @property
    def _tpu_parent(self) -> str:
        return (
            f"{_TPU_API}/projects/{self.project}/locations/{self.location}"
        )

    def _gke_pool(self, pool: str) -> str:
        return (
            f"{_GKE_API}/projects/{self.project}/locations/"
            f"{self.location}/clusters/{self.cluster}/nodePools/{pool}"
        )

    def _wait_operation(self, op: dict, api: str, timeout: float = 300.0):
        """Poll a long-running operation to completion (reference:
        wait_for_operation, node.py:342). TPU ops carry full names;
        GKE ops are project-relative."""
        def _check(done_op: dict) -> dict:
            if done_op.get("error"):
                raise RuntimeError(
                    f"operation {done_op.get('name')} failed: "
                    f"{done_op['error']}"
                )
            return done_op

        name = op.get("name", "")
        if op.get("done") or op.get("status") == "DONE" or not name:
            return _check(op)
        if api == "tpu":
            url = f"{_TPU_API}/{name}" if not name.startswith(
                "http"
            ) else name
        else:
            url = (
                f"{_GKE_API}/projects/{self.project}/locations/"
                f"{self.location}/operations/{name.rsplit('/', 1)[-1]}"
            )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = self.http.request("GET", url)
            if got.get("done") or got.get("status") == "DONE":
                return _check(got)
            time.sleep(self._poll_s)
        raise TimeoutError(f"operation {name} not done in {timeout}s")

    # -------------------------------------------------------- provider
    def create_node(self, node_type: str, resources: dict) -> str:
        pool = self.node_pools[node_type]
        mode = pool.get("mode", "queued_resource")
        if mode == "queued_resource":
            qr_id = f"ray-tpu-{self.cluster}-{uuid.uuid4().hex[:8]}"
            body = {
                "tpu": {
                    "nodeSpec": [
                        {
                            "parent": (
                                f"projects/{self.project}/locations/"
                                f"{self.location}"
                            ),
                            "nodeId": qr_id,
                            "node": {
                                "acceleratorType": pool["accelerator"],
                                "runtimeVersion": pool["runtime_version"],
                                "labels": {
                                    "ray-tpu-cluster": self.cluster,
                                    "ray-tpu-node-type": node_type,
                                },
                                "metadata": {
                                    "ray-tpu-provider-id": qr_id,
                                },
                            },
                        }
                    ]
                },
            }
            if pool.get("spot"):
                body["spot"] = {}
            if pool.get("reserved"):
                body["guaranteed"] = {"reserved": True}
            op = self.http.request(
                "POST",
                f"{self._tpu_parent}/queuedResources"
                f"?queuedResourceId={qr_id}",
                body,
            )
            # Creation of the QR record is quick; slice PROVISIONING is
            # minutes and is NOT awaited — the autoscaler's boot grace
            # covers it (update() credits unregistered capacity).
            self._wait_operation(op, "tpu")
            self._nodes[qr_id] = node_type
            return qr_id
        if mode == "node_pool":
            name = pool["pool"]
            got = self.http.request("GET", self._gke_pool(name))
            current = int(
                got.get("currentNodeCount", got.get("initialNodeCount", 0))
            )
            op = self.http.request(
                "POST",
                f"{self._gke_pool(name)}:setSize",
                {"nodeCount": current + 1},
            )
            self._wait_operation(op, "gke")
            # Pool members are fungible (GKE picks scale-down victims):
            # ids are slot-indexed and derivable from the pool size, so
            # a restarted provider reconstructs them from the API.
            pid = f"{name}#{current}"
            self._nodes[pid] = node_type
            return pid
        raise ValueError(f"unknown provider mode {mode!r}")

    def terminate_node(self, provider_node_id: str) -> None:
        # The id SHAPE routes the call (not the in-memory cache, which
        # a restarted provider no longer has): "<pool>#<i>" is a GKE
        # pool slot, anything else is a queued resource.
        if "#" in provider_node_id:
            name = provider_node_id.split("#", 1)[0]
            if name not in self._pool_types:
                raise ValueError(
                    f"unknown node pool in id {provider_node_id!r}"
                )
            got = self.http.request("GET", self._gke_pool(name))
            current = int(
                got.get("currentNodeCount", got.get("initialNodeCount", 0))
            )
            op = self.http.request(
                "POST",
                f"{self._gke_pool(name)}:setSize",
                {"nodeCount": max(0, current - 1)},
            )
            self._wait_operation(op, "gke")
            self._nodes.pop(provider_node_id, None)
            return
        try:
            op = self.http.request(
                "DELETE",
                f"{self._tpu_parent}/queuedResources/"
                f"{provider_node_id}?force=true",
            )
            self._wait_operation(op, "tpu")
        except GcpHttpError as e:
            if e.status != 404:  # already gone is success
                raise
        self._nodes.pop(provider_node_id, None)

    def non_terminated_nodes(self) -> dict[str, str]:
        """Authoritative membership from the API, label-filtered
        (reference: list_instances filter on ray cluster-name label,
        node.py:378). Queued resources in a terminal-failed state are
        dropped; node_pool members are synthesized from pool size."""
        out: dict[str, str] = {}
        modes = {p.get("mode", "queued_resource") for p in
                 self.node_pools.values()}
        if "queued_resource" in modes:
            items: list = []
            page = ""
            while True:
                url = f"{self._tpu_parent}/queuedResources"
                if page:
                    url += f"?pageToken={page}"
                got = self.http.request("GET", url)
                items.extend(got.get("queuedResources", []))
                page = got.get("nextPageToken", "")
                if not page:
                    break
            for qr in items:
                nodes = qr.get("tpu", {}).get("nodeSpec", [])
                if not nodes:
                    continue
                labels = nodes[0].get("node", {}).get("labels", {})
                if labels.get("ray-tpu-cluster") != self.cluster:
                    continue
                state = qr.get("state", {}).get("state", "")
                if state in ("FAILED", "SUSPENDED"):
                    continue
                qr_id = qr["name"].rsplit("/", 1)[-1]
                out[qr_id] = labels.get("ray-tpu-node-type", "")
        # node_pool members synthesized from the LIVE pool size, so a
        # restarted provider sees existing slices instead of re-adding
        # (and later being unable to reap) them.
        for name, node_type in self._pool_types.items():
            got = self.http.request("GET", self._gke_pool(name))
            count = int(
                got.get("currentNodeCount", got.get("initialNodeCount", 0))
            )
            for i in range(count):
                out[f"{name}#{i}"] = node_type
        return out

    def runtime_node_id(self, provider_node_id: str) -> str | None:
        """Map to the runtime node that registered from this slice: the
        node's labels carry the provider id (GCE metadata →
        detect_labels)."""
        if self._runtime_lookup is not None:
            return self._runtime_lookup(provider_node_id)
        try:
            from ray_tpu import api as core_api

            rt = core_api._runtime
            if not rt.ready:
                return None
            table = rt.run(rt.core.head.call("node_table"), 5)
        except Exception:  # noqa: BLE001 - mapping is best-effort
            return None
        for nid, n in table.items():
            if (
                n.get("labels", {}).get("ray-tpu-provider-id")
                == provider_node_id
            ):
                return nid
        return None
