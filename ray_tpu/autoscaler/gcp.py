"""GCP/GKE TPU node provider: real REST calls behind a transport seam.

Reference shape: python/ray/autoscaler/_private/gcp/node_provider.py +
node.py — a GCPResource per API (compute/tpu) doing REST calls through
an authorized http object, with operation polling and label-based
cluster membership; tpu_command_runner.py handles multi-host slices.
TPU-native differences here:

- The scaling unit is a SLICE, never a VM. Two provisioning paths:
  * ``queued_resource`` (Cloud TPU API v2 ``queuedResources``) — the
    modern way to obtain slices, including spot/reserved queueing
    (reference node.py:785 uses the older projects.locations.nodes).
  * ``node_pool`` (GKE ``nodePools:setSize``) — TPU slice node pools
    in a GKE cluster; one size increment = one slice replica.
- Every created queued resource is labeled with the ray_tpu cluster
  name and node type, so membership listing is a label filter, and the
  provider id is stamped into instance metadata — the node daemon's
  detect_labels probes GCE metadata (node.py _gce_metadata_labels) and
  registers it as a node label, which runtime_node_id matches against
  the head's node table. node_pool mode cannot stamp per-increment
  metadata (setSize is anonymous); instead the daemon registers its
  GCE instance name (``ray-tpu-gce-instance`` label) and the provider
  ids carry the instance name when the pool exposes its instance
  groups, so scale-down can target the exact idle instance.

``queued_resource`` is the RECOMMENDED mode: creation is atomic (one
QR per create) and deletion names the slice. ``node_pool`` rides
GKE's setSize, whose read-modify-write is guarded here by a per-pool
lock, conflict retry, and a post-resize verification re-read; when
the pool response carries ``instanceGroupUrls``, scale-down uses the
managed-instance-group ``deleteInstances`` call on the specific
victim instead of an anonymous shrink.

Auth rides a bearer token: ``GOOGLE_OAUTH_ACCESS_TOKEN`` env when set
(CI/dev), else the GCE metadata server (in-cluster). CI never talks to
Google: tests drive the provider through RecordedTransport fixtures.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
import uuid
from typing import Any, Callable

from ray_tpu.autoscaler.providers import NodeProvider

logger = logging.getLogger("ray_tpu.autoscaler")

_TPU_API = "https://tpu.googleapis.com/v2"
_GKE_API = "https://container.googleapis.com/v1"
_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/service-accounts/default/token"
)


_MAINTENANCE_EVENT_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/maintenance-event"
)
_PREEMPTED_URL = (
    "http://metadata.google.internal/computeMetadata/v1/"
    "instance/preempted"
)


class GceMaintenanceEventSource:
    """Preemption-notice source for the node daemon's drain watcher
    (runtime/node.py): polls the GCE metadata server's maintenance-event
    and preempted endpoints. A value other than NONE/FALSE means this VM
    is about to be migrated or preempted — the node self-reports DRAIN
    with the standard notice window so the trainer's emergency
    checkpoint and the autoscaler's replacement both start inside it.

    Only constructed on GCE hosts (the DMI product-name gate in
    NodeManager._preemption_source keeps other machines off the
    metadata endpoint). ``fetch`` is a seam for tests."""

    interval_s = 5.0

    def __init__(self, fetch: Callable[[str], str] | None = None):
        self._fetch = fetch or self._metadata_get

    @staticmethod
    def _metadata_get(url: str) -> str:
        req = urllib.request.Request(
            url, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=2) as resp:
            return resp.read().decode().strip()

    def poll(self, node) -> "tuple[str, float] | None":
        del node
        from ray_tpu._private import config

        try:
            if self._fetch(_PREEMPTED_URL).upper() == "TRUE":
                return ("gce-preempted", config.get("DRAIN_DEADLINE_S"))
        except OSError:
            pass
        try:
            event = self._fetch(_MAINTENANCE_EVENT_URL)
        except OSError:
            return None
        if event and event.upper() != "NONE":
            return (
                f"gce-maintenance:{event}",
                config.get("DRAIN_DEADLINE_S"),
            )
        return None


class GcpHttpError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:500]}")
        self.status = status
        self.body = body

    def is_conflict(self) -> bool:
        """GKE rejects a mutation while another cluster operation is in
        flight (409/412, or 400 FAILED_PRECONDITION whose message names
        the running operation). These are safe to retry after a
        re-read. Plain 400 validation errors are NOT retryable — only
        the operation-in-flight phrasing qualifies."""
        if self.status in (409, 412, 429):
            return True
        if self.status != 400:
            return False
        # Only the operation-in-flight phrasing qualifies; a permanent
        # FAILED_PRECONDITION (pool managed by cluster autoscaling,
        # pool being deleted, ...) must surface immediately.
        body = self.body.lower()
        return "operation" in body and (
            "in progress" in body or "running" in body or "wait" in body
        )


class GcpTransport:
    """Minimal authorized REST transport (the AuthorizedHttp analogue,
    reference node.py:240)."""

    def __init__(
        self,
        token_provider: Callable[[], "str | tuple[str, float]"] | None = None,
    ):
        self._token_provider = token_provider or self._default_token
        self._token: str | None = None
        self._token_expiry = 0.0

    @staticmethod
    def _default_token() -> tuple[str, float]:
        import os

        env = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env:
            return env, 600.0
        req = urllib.request.Request(
            _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        return payload["access_token"], float(payload.get("expires_in", 600))

    def _bearer(self) -> str:
        if self._token is None or time.time() > self._token_expiry:
            got = self._token_provider()
            # Providers may return a bare token or (token, expires_in).
            token, expires_in = got if isinstance(got, tuple) else (got, 600.0)
            self._token = token
            # Honor the server's actual lifetime, minus a safety margin so
            # a token fetched near expiry isn't cached past its death.
            self._token_expiry = time.time() + max(expires_in - 60.0, 10.0)
        return self._token

    def _invalidate_token(self) -> None:
        self._token = None
        self._token_expiry = 0.0

    def request(
        self, method: str, url: str, body: dict | None = None
    ) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        for attempt in (0, 1):
            req = urllib.request.Request(
                url,
                data=data,
                method=method,
                headers={
                    "Authorization": f"Bearer {self._bearer()}",
                    "Content-Type": "application/json",
                },
            )
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                break
            except urllib.error.HTTPError as e:
                if e.code == 401 and attempt == 0:
                    # Stale cached token: drop it and retry once fresh.
                    self._invalidate_token()
                    continue
                raise GcpHttpError(e.code, e.read().decode("utf-8", "replace"))
        return json.loads(payload) if payload else {}


class RecordedTransport:
    """Replays a recorded call script (CI has zero egress). Each entry:
    {"method", "url", "response", optional "body_contains"}. Calls must
    arrive in order; mismatches raise with the diff."""

    def __init__(self, script: list[dict]):
        self.script = list(script)
        self.calls: list[tuple] = []
        self._i = 0

    def request(
        self, method: str, url: str, body: dict | None = None
    ) -> dict:
        self.calls.append((method, url, body))
        if self._i >= len(self.script):
            raise AssertionError(
                f"unexpected extra call #{self._i}: {method} {url}"
            )
        expect = self.script[self._i]
        self._i += 1
        if expect["method"] != method or expect["url"] != url:
            raise AssertionError(
                f"call #{self._i - 1}: got {method} {url}, expected "
                f"{expect['method']} {expect['url']}"
            )
        for fragment in expect.get("body_contains", ()):
            if fragment not in json.dumps(body or {}):
                raise AssertionError(
                    f"call #{self._i - 1}: body missing {fragment!r}: "
                    f"{body}"
                )
        if "error_status" in expect:
            raise GcpHttpError(expect["error_status"], expect.get(
                "error_body", ""
            ))
        return expect["response"]

    def assert_done(self):
        if self._i != len(self.script):
            raise AssertionError(
                f"{len(self.script) - self._i} scripted calls never "
                f"made: {self.script[self._i:]}"
            )


class GkeTpuNodeProvider(NodeProvider):
    """TPU-slice provider over the GKE / Cloud TPU REST surface.

    ``node_pools`` maps node_type → pool spec:

        {"mode": "queued_resource", "accelerator": "v5litepod-8",
         "runtime_version": "v2-alpha-tpuv5-lite", "spot": False}
      or
        {"mode": "node_pool", "pool": "tpu-v5e-8"}

    Slice semantics: one create_node == one whole slice (all its hosts
    share ICI and live or die together, reference util/tpu.py
    SlicePlacementGroup); terminate reaps the slice as a unit.
    """

    def __init__(
        self,
        project: str,
        location: str,
        cluster: str,
        node_pools: dict[str, dict],
        transport=None,
        runtime_lookup: Callable[[str], str | None] | None = None,
        operation_poll_s: float = 2.0,
        node_table_cache_s: float = 2.0,
    ):
        self.project = project
        self.location = location
        self.cluster = cluster
        self.node_pools = node_pools
        self.http = transport or GcpTransport()
        self._runtime_lookup = runtime_lookup
        self._poll_s = operation_poll_s
        # One reconcile tick calls runtime_node_id once per tracked
        # slice; fetching + scanning the whole node table each time is
        # O(tracked x nodes) head RPCs. Cache a label index briefly.
        self._node_cache_s = node_table_cache_s
        self._label_index: dict[str, str] = {}
        self._label_index_expiry = 0.0
        # setSize is an absolute write: serialize our own resizes per
        # pool so two concurrent reconciles cannot interleave their
        # read-modify-write windows inside this process.
        self._pool_locks: dict[str, threading.RLock] = {}
        self._pool_locks_guard = threading.Lock()
        # provider_node_id → node_type cache of our own creations; the
        # authoritative list always comes from the API
        # (non_terminated_nodes), so a restarted provider process
        # re-discovers existing slices instead of leaking them.
        self._nodes: dict[str, str] = {}
        # pool name → pre-grow membership snapshot, recorded when a
        # successful setSize(+1)'s new instance never surfaced in the
        # lagging MIG listing. The next create_node claims an instance
        # outside this basis (and untracked) instead of resizing again
        # — the basis is what distinguishes OUR lagged instance from
        # pre-existing members this provider never created. In-memory
        # only: after a restart the orphan is simply a normal pool
        # member visible through non_terminated_nodes.
        self._pending_grow: dict[str, frozenset] = {}
        # pool name → node_type reverse map for node_pool-mode ids
        # ("<pool>#<i>"), stable across provider restarts.
        self._pool_types = {
            spec["pool"]: nt
            for nt, spec in node_pools.items()
            if spec.get("mode") == "node_pool"
        }

    # ------------------------------------------------------------ paths
    @property
    def _tpu_parent(self) -> str:
        return (
            f"{_TPU_API}/projects/{self.project}/locations/{self.location}"
        )

    def _gke_pool(self, pool: str) -> str:
        return (
            f"{_GKE_API}/projects/{self.project}/locations/"
            f"{self.location}/clusters/{self.cluster}/nodePools/{pool}"
        )

    def _wait_operation(self, op: dict, api: str, timeout: float = 300.0):
        """Poll a long-running operation to completion (reference:
        wait_for_operation, node.py:342). TPU ops carry full names;
        GKE ops are project-relative."""
        def _check(done_op: dict) -> dict:
            if done_op.get("error"):
                raise RuntimeError(
                    f"operation {done_op.get('name')} failed: "
                    f"{done_op['error']}"
                )
            return done_op

        name = op.get("name", "")
        if op.get("done") or op.get("status") == "DONE" or not name:
            return _check(op)
        if api == "tpu":
            url = f"{_TPU_API}/{name}" if not name.startswith(
                "http"
            ) else name
        elif api == "compute":
            # Compute zonal ops (deleteInstances) carry a selfLink.
            url = op.get("selfLink") or name
            if not url.startswith("http"):
                return _check(op)
        else:
            url = (
                f"{_GKE_API}/projects/{self.project}/locations/"
                f"{self.location}/operations/{name.rsplit('/', 1)[-1]}"
            )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            got = self.http.request("GET", url)
            if got.get("done") or got.get("status") == "DONE":
                return _check(got)
            time.sleep(self._poll_s)
        raise TimeoutError(f"operation {name} not done in {timeout}s")

    # ----------------------------------------------------- pool helpers
    def _pool_lock(self, name: str) -> threading.RLock:
        # Reentrant: create_node holds it across its read-diff-resize
        # sequence while _resize_pool re-acquires inside.
        with self._pool_locks_guard:
            return self._pool_locks.setdefault(name, threading.RLock())

    @staticmethod
    def _pool_count(got: dict) -> int:
        return int(
            got.get("currentNodeCount", got.get("initialNodeCount", 0))
        )

    def _list_pool_instances(
        self, pool_resp: dict
    ) -> "dict[str, tuple[str, str]] | None":
        """instance name → (instance_url, igm_url) for every managed
        instance backing the pool, or None when the pool response does
        not expose instance groups (then ids stay slot-indexed and
        scale-down falls back to an anonymous shrink)."""
        igs = pool_resp.get("instanceGroupUrls")
        if not igs:
            return None
        out: dict[str, tuple[str, str]] = {}
        for ig in igs:
            igm = ig.replace("/instanceGroups/", "/instanceGroupManagers/")
            got = self.http.request("POST", f"{igm}/listManagedInstances")
            for mi in got.get("managedInstances", []):
                inst_url = mi.get("instance", "")
                if inst_url:
                    out[inst_url.rsplit("/", 1)[-1]] = (inst_url, igm)
        return out

    def _resize_pool(self, name: str, delta: int) -> "tuple[int, dict]":
        """Conflict-safe GET → setSize(current+delta) → verify re-read.

        setSize is an absolute write, so the GET/POST window can lose a
        concurrent increment (another reconcile, an operator's kubectl).
        Three guards: a per-pool lock (in-process interleavings), retry
        on GKE's operation-in-flight conflicts, and a post-resize
        re-read — if the observed count moved the wrong way, the write
        was clobbered and the whole read-modify-write retries from a
        fresh read. The GET always happens INSIDE the lock: a count
        fetched before acquisition could be stale by the time the write
        goes out, which is the exact lost-update this guards against.
        Returns (size_before_our_write, verify_response).
        """
        with self._pool_lock(name):
            last_exc: Exception | None = None
            for attempt in range(4):
                # tpulint: allow(blocking-under-lock reason=the pool lock exists to hold the remote GET-setSize-verify window closed; releasing it around the REST calls reintroduces the lost-update race it prevents)
                got = self.http.request("GET", self._gke_pool(name))
                current = self._pool_count(got)
                target = max(0, current + delta)
                if target == current:
                    # Clamped no-op (scale-down of an already-empty
                    # pool): nothing to write, and the verify heuristic
                    # below would misread observed==current as a lost
                    # update.
                    return current, got
                try:
                    # tpulint: allow(blocking-under-lock reason=the setSize write IS the critical section the pool lock serializes)
                    op = self.http.request(
                        "POST",
                        f"{self._gke_pool(name)}:setSize",
                        {"nodeCount": target},
                    )
                except GcpHttpError as e:
                    if e.is_conflict():
                        last_exc = e
                        # tpulint: allow(blocking-under-lock reason=conflict backoff must keep the lock - another thread resizing during it would interleave its read into our retry window)
                        time.sleep(self._poll_s * (attempt + 1))
                        continue
                    raise
                self._wait_operation(op, "gke")
                # tpulint: allow(blocking-under-lock reason=the verify re-read belongs to the same locked read-modify-write window as the setSize above)
                verify = self.http.request("GET", self._gke_pool(name))
                observed = self._pool_count(verify)
                # observed == current (our write apparently never
                # happened) is the one unambiguous lost-update
                # signature — retry from a fresh read. Any OTHER
                # mismatch means a racing writer moved the count after
                # our write landed; re-applying the delta would
                # double-resize (e.g. delete a second node for one
                # terminate), so accept the observed state and let the
                # autoscaler's next reconcile tick correct any residual
                # drift through non_terminated_nodes.
                if observed != current or delta == 0:
                    return current, verify
                last_exc = RuntimeError(
                    f"pool {name} resize lost: wrote {target}, "
                    f"observed {observed}"
                )
                # tpulint: allow(blocking-under-lock reason=lost-update backoff keeps the lock so the fresh re-read stays serialized with other local resizes)
                time.sleep(self._poll_s * (attempt + 1))
            raise RuntimeError(
                f"pool {name} resize failed after 4 attempts"
            ) from last_exc

    # -------------------------------------------------------- provider
    def create_node(self, node_type: str, resources: dict) -> str:
        pool = self.node_pools[node_type]
        mode = pool.get("mode", "queued_resource")
        if mode == "queued_resource":
            qr_id = f"ray-tpu-{self.cluster}-{uuid.uuid4().hex[:8]}"
            body = {
                "tpu": {
                    "nodeSpec": [
                        {
                            "parent": (
                                f"projects/{self.project}/locations/"
                                f"{self.location}"
                            ),
                            "nodeId": qr_id,
                            "node": {
                                "acceleratorType": pool["accelerator"],
                                "runtimeVersion": pool["runtime_version"],
                                "labels": {
                                    "ray-tpu-cluster": self.cluster,
                                    "ray-tpu-node-type": node_type,
                                },
                                "metadata": {
                                    "ray-tpu-provider-id": qr_id,
                                },
                            },
                        }
                    ]
                },
            }
            if pool.get("spot"):
                body["spot"] = {}
            if pool.get("reserved"):
                body["guaranteed"] = {"reserved": True}
            op = self.http.request(
                "POST",
                f"{self._tpu_parent}/queuedResources"
                f"?queuedResourceId={qr_id}",
                body,
            )
            # Creation of the QR record is quick; slice PROVISIONING is
            # minutes and is NOT awaited — the autoscaler's boot grace
            # covers it (update() credits unregistered capacity).
            self._wait_operation(op, "tpu")
            self._nodes[qr_id] = node_type
            return qr_id
        if mode == "node_pool":
            name = pool["pool"]
            # The before-snapshot, resize, and after-diff must be one
            # critical section: with the lock taken only inside
            # _resize_pool, two concurrent creates could share a
            # before-set and pick the SAME new instance as their id.
            with self._pool_lock(name):
                # tpulint: allow(blocking-under-lock reason=the before-snapshot must be read inside the lock or two creates could share it and claim the same new instance)
                got = self.http.request("GET", self._gke_pool(name))
                before = self._list_pool_instances(got)
                if before is not None and name in self._pending_grow:
                    # A previous create grew the pool but the MIG
                    # listing never surfaced the instance. Claim an
                    # orphan (listed, outside the pre-grow basis, and
                    # untracked) instead of resizing again — the second
                    # setSize is how capacity leaks.
                    basis = self._pending_grow[name]
                    for attempt in range(5):
                        if attempt:
                            # tpulint: allow(blocking-under-lock reason=orphan-claim re-reads poll a lagging MIG listing; the lock must stay held so a concurrent create cannot claim the same orphan)
                            time.sleep(self._poll_s)
                            # tpulint: allow(blocking-under-lock reason=same locked orphan-claim window as the sleep above)
                            got = self.http.request(
                                "GET", self._gke_pool(name)
                            )
                            before = (
                                self._list_pool_instances(got) or {}
                            )
                        orphans = sorted(
                            inst for inst in set(before) - basis
                            if f"{name}#{inst}" not in self._nodes
                        )
                        if orphans:
                            del self._pending_grow[name]
                            pid = f"{name}#{orphans[0]}"
                            self._nodes[pid] = node_type
                            return pid
                    if self._pool_count(got) <= len(basis):
                        # The pool no longer holds the extra capacity
                        # (operator resize-down, quota rollback, MIG
                        # repair): the pending grow is gone for good.
                        # Clear it and fall through to a fresh resize —
                        # without this the pool is wedged until the
                        # provider restarts. (If the count is still
                        # above the basis, the capacity exists and only
                        # the listing lags: resizing now WOULD leak, so
                        # keep waiting across retries instead.)
                        del self._pending_grow[name]
                    else:
                        raise RuntimeError(
                            f"pool {name} has a pending grown instance"
                            " the managed-instance listing still does"
                            " not show"
                        )
                current, verify = self._resize_pool(name, +1)
                if before is not None:
                    # Instance-backed id: the instance the resize added,
                    # picked deterministically so the id stays
                    # consistent with instance-named membership listing.
                    # MIG listings can lag the resize, so re-read a few
                    # times; a slot-id fallback here would never match
                    # non_terminated_nodes and the autoscaler would
                    # treat the node as failed, so raise instead and
                    # let the reconcile retry cleanly.
                    for attempt in range(5):
                        if attempt:
                            # tpulint: allow(blocking-under-lock reason=naming the just-added instance re-reads a lagging MIG listing; dropping the lock would let a racing create adopt it)
                            time.sleep(self._poll_s)
                            # tpulint: allow(blocking-under-lock reason=same locked post-resize naming window as the sleep above)
                            verify = self.http.request(
                                "GET", self._gke_pool(name)
                            )
                        after = self._list_pool_instances(verify) or {}
                        new = sorted(set(after) - set(before))
                        if new:
                            pid = f"{name}#{new[0]}"
                            self._nodes[pid] = node_type
                            return pid
                    # The resize succeeded but we cannot name the new
                    # instance. Do NOT shrink: an anonymous setSize(-1)
                    # lets GKE pick the scale-in victim, which can kill
                    # a tracked busy slice while the new instance
                    # survives (the same hazard targeted scale-down
                    # exists to prevent). Record the grow instead so
                    # the reconcile retry CLAIMS the orphan rather than
                    # resizing +1 again — no compounding leak, and the
                    # instance surfaces in non_terminated_nodes once
                    # the listing catches up.
                    self._pending_grow[name] = frozenset(before)
                    raise RuntimeError(
                        f"pool {name} grew to {self._pool_count(verify)}"
                        " but the managed-instance listing never showed"
                        " the new instance (grow recorded; the retry"
                        " will claim it instead of resizing again)"
                    )
                # No instance groups exposed: slot-indexed ids,
                # derivable from the pool size, stable across provider
                # restarts.
                pid = f"{name}#{current}"
                self._nodes[pid] = node_type
                return pid
        raise ValueError(f"unknown provider mode {mode!r}")

    def terminate_node(self, provider_node_id: str) -> None:
        # The id SHAPE routes the call (not the in-memory cache, which
        # a restarted provider no longer has): "<pool>#<i>" is a GKE
        # pool slot, anything else is a queued resource.
        if "#" in provider_node_id:
            name, token = provider_node_id.split("#", 1)
            if name not in self._pool_types:
                raise ValueError(
                    f"unknown node pool in id {provider_node_id!r}"
                )
            got = self.http.request("GET", self._gke_pool(name))
            instances = self._list_pool_instances(got)
            if instances is not None:
                entry = instances.get(token)
                if entry is None and token.isdigit():
                    # Legacy slot id: map slot i to the i-th instance in
                    # name order (the same order membership listing
                    # would have assigned slots).
                    names = sorted(instances)
                    if int(token) < len(names):
                        entry = instances[names[int(token)]]
                if entry is not None:
                    inst_url, igm = entry
                    # Targeted removal: the MIG deletes THIS instance
                    # and decrements the target size — GKE cannot pick
                    # a busy slice as the victim.
                    with self._pool_lock(name):
                        # tpulint: allow(blocking-under-lock reason=targeted deleteInstances must not interleave with a concurrent resize of the same pool - the lock scope is the API call by design)
                        op = self.http.request(
                            "POST",
                            f"{igm}/deleteInstances",
                            {
                                "instances": [inst_url],
                                "skipInstancesOnValidationError": True,
                            },
                        )
                        self._wait_operation(op, "compute")
                else:
                    # The named instance no longer exists: the terminate
                    # already happened (retried call, provider restart).
                    # An anonymous shrink here would delete an ARBITRARY
                    # live instance — exactly what targeted scale-down
                    # exists to prevent. Treat as done.
                    pass
                self._nodes.pop(provider_node_id, None)
                return
            # No instance groups exposed: anonymous conflict-safe shrink
            # is the best the API offers.
            self._resize_pool(name, -1)
            self._nodes.pop(provider_node_id, None)
            return
        try:
            op = self.http.request(
                "DELETE",
                f"{self._tpu_parent}/queuedResources/"
                f"{provider_node_id}?force=true",
            )
            self._wait_operation(op, "tpu")
        except GcpHttpError as e:
            if e.status != 404:  # already gone is success
                raise
        self._nodes.pop(provider_node_id, None)

    def terminate_nodes(self, provider_node_ids: "list[str]") -> None:
        """Batch termination for a fully-drained slice: every
        "<pool>#<instance>" id of the same pool collapses into ONE
        targeted deleteInstances call per managed instance group (the
        API takes a list) — a drained 32-host pool slice costs one API
        round-trip, not 32. Queued-resource ids are ALREADY whole
        slices (one DELETE each is the unit call); ids whose instance
        cannot be batch-resolved (legacy slot ids past the listing,
        pools without instance groups) fall back to the single-node
        path, which never anonymously shrinks."""
        by_pool: dict[str, list[str]] = {}
        rest: list[str] = []
        for pid in provider_node_ids:
            if "#" in pid and pid.split("#", 1)[0] in self._pool_types:
                by_pool.setdefault(pid.split("#", 1)[0], []).append(pid)
            else:
                rest.append(pid)
        for name, pids in by_pool.items():
            if len(pids) == 1:
                self.terminate_node(pids[0])
                continue
            got = self.http.request("GET", self._gke_pool(name))
            instances = self._list_pool_instances(got)
            if instances is None:
                # No instance groups exposed: only the anonymous-shrink
                # single-node path exists.
                for pid in pids:
                    self.terminate_node(pid)
                continue
            names_sorted = sorted(instances)
            calls: dict[str, list[str]] = {}  # igm → instance urls
            for pid in pids:
                token = pid.split("#", 1)[1]
                entry = instances.get(token)
                if entry is None and token.isdigit():
                    # Legacy slot id: i-th instance in name order.
                    if int(token) < len(names_sorted):
                        entry = instances[names_sorted[int(token)]]
                if entry is None:
                    # Named instance already gone: the terminate already
                    # happened (retried call, provider restart).
                    self._nodes.pop(pid, None)
                    continue
                inst_url, igm = entry
                calls.setdefault(igm, []).append(inst_url)
            with self._pool_lock(name):
                for igm, urls in calls.items():
                    # tpulint: allow(blocking-under-lock reason=the batched deleteInstances must not interleave with a concurrent resize of the same pool - same critical section as the single-node path)
                    op = self.http.request(
                        "POST",
                        f"{igm}/deleteInstances",
                        {
                            "instances": urls,
                            "skipInstancesOnValidationError": True,
                        },
                    )
                    # tpulint: allow(blocking-under-lock reason=operation wait belongs to the same locked deleteInstances window as the call above)
                    self._wait_operation(op, "compute")
            for pid in pids:
                self._nodes.pop(pid, None)
        for pid in rest:
            self.terminate_node(pid)

    def non_terminated_nodes(self) -> dict[str, str]:
        """Authoritative membership from the API, label-filtered
        (reference: list_instances filter on ray cluster-name label,
        node.py:378). Queued resources in a terminal-failed state are
        dropped; node_pool members are synthesized from pool size."""
        out: dict[str, str] = {}
        modes = {p.get("mode", "queued_resource") for p in
                 self.node_pools.values()}
        if "queued_resource" in modes:
            items: list = []
            page = ""
            while True:
                url = f"{self._tpu_parent}/queuedResources"
                if page:
                    url += f"?pageToken={page}"
                got = self.http.request("GET", url)
                items.extend(got.get("queuedResources", []))
                page = got.get("nextPageToken", "")
                if not page:
                    break
            for qr in items:
                nodes = qr.get("tpu", {}).get("nodeSpec", [])
                if not nodes:
                    continue
                labels = nodes[0].get("node", {}).get("labels", {})
                if labels.get("ray-tpu-cluster") != self.cluster:
                    continue
                state = qr.get("state", {}).get("state", "")
                if state in ("FAILED", "SUSPENDED"):
                    continue
                qr_id = qr["name"].rsplit("/", 1)[-1]
                out[qr_id] = labels.get("ray-tpu-node-type", "")
        # node_pool members from the LIVE pool (instance names when the
        # pool exposes its instance groups, else synthesized slots), so
        # a restarted provider sees existing slices instead of
        # re-adding (and later being unable to reap) them.
        for name, node_type in self._pool_types.items():
            got = self.http.request("GET", self._gke_pool(name))
            instances = self._list_pool_instances(got)
            if instances is not None:
                for inst in instances:
                    out[f"{name}#{inst}"] = node_type
            else:
                for i in range(self._pool_count(got)):
                    out[f"{name}#{i}"] = node_type
        return out

    def runtime_node_id(self, provider_node_id: str) -> str | None:
        """Map to the runtime node that registered from this slice: the
        node's labels carry the provider id (queued_resource mode, GCE
        metadata → detect_labels) or the GCE instance name (node_pool
        mode). The node table is fetched once per cache window and
        indexed by label, not rescanned per provider id."""
        if self._runtime_lookup is not None:
            return self._runtime_lookup(provider_node_id)
        index = self._node_label_index()
        hit = index.get(provider_node_id)
        if hit is None and "#" in provider_node_id:
            # node_pool ids carry the instance name after '#'.
            hit = index.get(provider_node_id.split("#", 1)[1])
        return hit

    def _node_label_index(self) -> dict[str, str]:
        """provider-id-label / gce-instance-label → runtime node id,
        cached for node_table_cache_s (one head RPC per reconcile tick
        instead of one per tracked slice)."""
        now = time.monotonic()
        if now < self._label_index_expiry:
            return self._label_index
        try:
            from ray_tpu import api as core_api

            rt = core_api._runtime
            if not rt.ready:
                return {}
            table = rt.run(rt.core.head.call("node_table"), 5)
        except Exception:  # noqa: BLE001 - mapping is best-effort
            logger.debug(
                "node-label index unavailable (head busy?); provider-id "
                "mapping degrades to unmapped this tick", exc_info=True,
            )
            return {}
        index: dict[str, str] = {}
        for nid, n in table.items():
            labels = n.get("labels", {})
            for key in ("ray-tpu-provider-id", "ray-tpu-gce-instance"):
                if labels.get(key):
                    index[labels[key]] = nid
        self._label_index = index
        self._label_index_expiry = now + self._node_cache_s
        return index
