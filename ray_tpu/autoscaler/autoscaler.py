"""The autoscaler control loop.

Reference: v2 Autoscaler (autoscaler/v2/autoscaler.py:50): each tick,
read cluster resource state from the head, bin-pack unmet demand into new
nodes (scheduler.py), launch via the provider, and reap nodes idle past
the timeout. Runs in the driver process as a plain thread-driven loop
(the reference runs it in the monitor process on the head node).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger("ray_tpu.autoscaler")

from ray_tpu import api as core_api
from ray_tpu.autoscaler.providers import NodeProvider
from ray_tpu.autoscaler.scheduler import fit_demand
from ray_tpu.util.metrics import Gauge

_CHRONIC_STRAGGLER = Gauge(
    "ray_tpu_autoscaler_chronic_straggler",
    "slowest/missing collective-contribution count of a node flagged "
    "for replacement",
    tag_keys=("node",),
)


@dataclass
class NodeTypeConfig:
    resources: dict
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class _TrackedNode:
    provider_id: str
    node_type: str
    idle_since: float | None = None
    launched_at: float = field(default_factory=time.monotonic)


class Autoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        node_types: dict[str, NodeTypeConfig],
        *,
        idle_timeout_s: float = 30.0,
        interval_s: float = 1.0,
        boot_grace_s: float = 600.0,
        straggler_threshold: int = 20,
        straggler_drain: bool = True,
        straggler_drain_deadline_s: float = 120.0,
    ):
        self.provider = provider
        self.node_types = node_types
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        self.boot_grace_s = boot_grace_s
        # A node whose collective_straggler_total (slowest or missing
        # contributor, summed across its ranks/groups) reaches this is
        # flagged as a chronic straggler — and, with straggler_drain on,
        # DRAINED through the head and replaced through the provider
        # (drain-and-replace, not just log-and-gauge).
        self.straggler_threshold = straggler_threshold
        self.straggler_drain = straggler_drain
        self.straggler_drain_deadline_s = straggler_drain_deadline_s
        self._flagged_stragglers: set[str] = set()
        self._drained_stragglers: set[str] = set()
        # Draining runtime node ids we already launched a replacement
        # for: one drain notice buys exactly one proactive launch.
        self._drain_replaced: set[str] = set()
        self._tracked: dict[str, _TrackedNode] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_status: dict = {}

    # ----------------------------------------------------------- control
    def start(self):
        # Adopt capacity that already exists (an autoscaler RESTART must
        # not double-provision slices it forgot, nor leak ones it can
        # no longer reap — the provider's API listing is authoritative).
        try:
            for pid, ntype in self.provider.non_terminated_nodes().items():
                if ntype in self.node_types and pid not in self._tracked:
                    self._tracked[pid] = _TrackedNode(pid, ntype)
        except Exception:  # noqa: BLE001 - provider may be offline
            logger.exception("could not list pre-existing nodes")
        for name, cfg in self.node_types.items():
            existing = sum(
                1 for t in self._tracked.values() if t.node_type == name
            )
            for _ in range(max(0, cfg.min_workers - existing)):
                self._launch(name)
        self._thread = threading.Thread(
            target=self._loop, name="ray_tpu_autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.update()
            except Exception as e:  # noqa: BLE001 - keep autoscaling alive
                logger.exception("autoscaler tick failed")
                self.last_status = {"error": repr(e), "ts": time.time()}

    # ------------------------------------------------------------- tick
    def _cluster_status(self) -> dict:
        rt = core_api._runtime

        async def go():
            return await rt.core.head.call("cluster_status")

        return rt.run(go())

    def _straggler_node_counts(self) -> dict[str, float]:
        """Per-node chronic-straggler counts from the head (summed
        collective_straggler_total resolved through the collective
        membership table)."""
        rt = core_api._runtime

        async def go():
            return await rt.core.head.call("collective_straggler_stats")

        try:
            return rt.run(go()).get("nodes") or {}
        except Exception:  # noqa: BLE001 - telemetry must not stop ticks
            logger.debug(
                "straggler stats unavailable this tick", exc_info=True
            )
            return {}

    def _check_stragglers(
        self, node_counts: dict[str, float]
    ) -> dict[str, float]:
        """Flag chronic collective stragglers (log once + gauge). The
        autoscaler does not kill them itself — a straggler is slow, not
        dead, and may host other work — it surfaces the replacement
        signal (metric + last_status) for the operator/policy layer."""
        chronic: dict[str, float] = {}
        for nid, count in node_counts.items():
            if count < self.straggler_threshold:
                continue
            chronic[nid] = count
            _CHRONIC_STRAGGLER.set(count, tags={"node": nid})
            if nid not in self._flagged_stragglers:
                self._flagged_stragglers.add(nid)
                logger.warning(
                    "node %s was the slowest/missing collective "
                    "contributor %d times (threshold %d): chronic "
                    "straggler, flagging for replacement",
                    nid[:12], int(count), self.straggler_threshold,
                )
        return chronic

    def _launch(self, node_type: str):
        pid = self.provider.create_node(
            node_type, self.node_types[node_type].resources
        )
        self._tracked[pid] = _TrackedNode(pid, node_type)

    def _drain_node_via_head(self, node_id: str, reason: str) -> bool:
        rt = core_api._runtime

        async def go():
            return await rt.core.head.call(
                "drain_node",
                node_id=node_id,
                reason=reason,
                deadline_s=self.straggler_drain_deadline_s,
            )

        try:
            return bool(rt.run(go()).get("ok"))
        except Exception:  # noqa: BLE001 - retried next tick
            logger.warning(
                "drain request for node %s failed; retrying next tick",
                node_id[:12], exc_info=True,
            )
            return False

    def _node_type_for(self, node_id: str, node: dict) -> str | None:
        """Which configured node type a runtime node corresponds to:
        the provider-tracked type when we launched it, else the first
        type whose resource shape the node covers (static nodes)."""
        for pid, tracked in self._tracked.items():
            if self.provider.runtime_node_id(pid) == node_id:
                return tracked.node_type
        for name, cfg in self.node_types.items():
            if all(
                node.get("resources", {}).get(k, 0) >= v
                for k, v in cfg.resources.items()
            ):
                return name
        return None

    @staticmethod
    def _drain_unit(nid: str, node: dict) -> str:
        """Replacement-dedupe key for a draining node: its SLICE label
        when it has one (the provider's create_node provisions a whole
        slice, so a slice going away buys exactly ONE launch however
        many hosts it has), else the node itself."""
        slice_id = (node.get("labels") or {}).get("slice")
        return f"slice:{slice_id}" if slice_id else nid

    def _handle_draining(
        self, draining: dict, nodes: dict, counts: dict[str, int]
    ) -> None:
        """Act on drain notices: (1) proactively provision a replacement
        per draining FAULT UNIT — one launch per draining slice (all its
        hosts drain together under slice fault domains; the replacement
        slice boots as a unit WHILE the old one finishes its work),
        else per node — and (2) terminate provider-owned drained nodes
        once they are empty or past their deadline."""
        now_wall = time.time()
        for nid, dinfo in draining.items():
            unit = self._drain_unit(nid, nodes.get(nid, {}))
            if unit in self._drain_replaced:
                continue
            self._drain_replaced.add(unit)
            ntype = self._node_type_for(nid, nodes.get(nid, {}))
            if ntype is None:
                continue
            if counts.get(ntype, 0) < self.node_types[ntype].max_workers:
                logger.info(
                    "%s draining (%s): provisioning a replacement %s",
                    unit if unit.startswith("slice:")
                    else f"node {nid[:12]}",
                    dinfo.get("reason", ""), ntype,
                )
                self._launch(ntype)
                counts[ntype] = counts.get(ntype, 0) + 1
        # Reap provider-owned drained nodes. Ignores min_workers — the
        # replacement is already tracked against the same type. Nodes
        # sharing a draining SLICE reap as ONE provider call
        # (terminate_nodes) once the whole unit is empty/expired: the
        # slice tears down as the unit it was provisioned as, not N
        # per-host API round-trips.
        unit_members: dict[str, list[str]] = {}
        unit_ready: dict[str, list[str]] = {}
        for pid, tracked in list(self._tracked.items()):
            rid = self.provider.runtime_node_id(pid)
            if rid is None or rid not in draining:
                continue
            node = nodes.get(rid)
            unit = self._drain_unit(rid, node or {})
            unit_members.setdefault(unit, []).append(pid)
            emptied = node is not None and not node.get("pending") and all(
                node["available"].get(k, 0) >= v
                for k, v in node["resources"].items()
            )
            expired = now_wall > draining[rid].get("deadline_ts", 0.0)
            if node is None or emptied or expired:
                unit_ready.setdefault(unit, []).append(pid)
        for unit, pids in unit_ready.items():
            if len(pids) < len(unit_members[unit]):
                # Part of the slice still holds work inside its notice
                # window: the unit reaps together on a later tick (the
                # drain deadline bounds the wait).
                continue
            logger.info(
                "terminating drained %s as one unit: %s",
                unit if unit.startswith("slice:") else f"node {unit[:12]}",
                pids,
            )
            try:
                self.provider.terminate_nodes(pids)
            finally:
                for pid in pids:
                    self._tracked.pop(pid, None)
        # Forget replacement markers for units no longer draining/alive.
        self._drain_replaced &= {
            self._drain_unit(nid, nodes.get(nid, {})) for nid in draining
        }

    def update(self):
        """One reconcile tick (public for deterministic tests)."""
        status = self._cluster_status()
        nodes = status["nodes"]
        draining = status.get("draining") or {}

        # Demand = per-node queued leases + cluster-wide unschedulable.
        demand = list(status.get("unschedulable", []))
        for n in nodes.values():
            demand.extend(n.get("pending", []))

        counts: dict[str, int] = {}
        for t in self._tracked.values():
            counts[t.node_type] = counts.get(t.node_type, 0) + 1

        # Chronic stragglers → drain-and-replace: the drain excludes the
        # node from new placements and fans the notice out; the generic
        # drain handling below provisions its replacement.
        chronic = self._check_stragglers(self._straggler_node_counts())
        if self.straggler_drain:
            for nid in chronic:
                if nid in self._drained_stragglers or nid not in nodes:
                    continue
                if self._drain_node_via_head(nid, "chronic straggler"):
                    self._drained_stragglers.add(nid)
                    draining = dict(draining)
                    draining.setdefault(
                        nid,
                        {
                            "reason": "chronic straggler",
                            "deadline_ts": time.time()
                            + self.straggler_drain_deadline_s,
                        },
                    )

        self._handle_draining(draining, nodes, counts)

        # A draining node's capacity is spoken for — counting it as free
        # would cancel out the very demand its replacement should absorb.
        free = [
            dict(n["available"])
            for nid, n in nodes.items()
            if nid not in draining
        ]
        # Credit capacity of launched-but-not-yet-registered nodes (real
        # providers take minutes to boot a slice): without this, every
        # tick re-launches for the same unmet demand. The credit expires
        # after boot_grace_s — a provider that cannot map provider ids to
        # runtime node ids (runtime_node_id → None) must not accrue
        # phantom capacity forever.
        registered = set(nodes)
        now = time.monotonic()
        for pid, tracked in list(self._tracked.items()):
            rid = self.provider.runtime_node_id(pid)
            if rid is not None and rid in registered:
                continue
            if now - tracked.launched_at < self.boot_grace_s:
                free.append(
                    dict(self.node_types[tracked.node_type].resources)
                )
            elif rid is not None:
                # Mappable provider, node never registered within the
                # grace window: a failed launch. Reap it — leaving it
                # tracked would pin a max_workers slot (and the cloud
                # bill) forever while contributing nothing.
                logger.warning(
                    "node %s (%s) failed to register within %.0fs; "
                    "terminating",
                    pid, tracked.node_type, self.boot_grace_s,
                )
                try:
                    self.provider.terminate_node(pid)
                finally:
                    del self._tracked[pid]
            # rid is None (provider can't map ids, e.g. the GKE stub):
            # keep it tracked but uncredited — reaping on a blind signal
            # would kill healthy registered nodes.
        to_add = fit_demand(
            demand,
            {
                name: {
                    "resources": cfg.resources,
                    "max_workers": cfg.max_workers,
                }
                for name, cfg in self.node_types.items()
            },
            counts,
            free,
        )
        for name, count in to_add.items():
            for _ in range(count):
                self._launch(name)

        # Idle termination: a provider-launched node whose available ==
        # total (nothing leased) for idle_timeout_s goes away, floored at
        # min_workers per type.
        now = time.monotonic()
        runtime_ids = {
            self.provider.runtime_node_id(pid): pid for pid in self._tracked
        }
        for nid, n in nodes.items():
            pid = runtime_ids.get(nid)
            if pid is None:
                continue
            tracked = self._tracked[pid]
            busy = any(
                n["available"].get(k, 0) < v
                for k, v in n["resources"].items()
            ) or n.get("pending")
            if busy:
                tracked.idle_since = None
            elif tracked.idle_since is None:
                tracked.idle_since = now

        for pid, tracked in list(self._tracked.items()):
            cfg = self.node_types[tracked.node_type]
            alive_of_type = sum(
                1
                for t in self._tracked.values()
                if t.node_type == tracked.node_type
            )
            if (
                tracked.idle_since is not None
                and now - tracked.idle_since > self.idle_timeout_s
                and alive_of_type > cfg.min_workers
            ):
                self.provider.terminate_node(pid)
                del self._tracked[pid]

        # Serve replica deficits (controller autoscale reports relayed
        # through cluster_status): a deployment below target means its
        # replica leases are or will be in the demand list above — the
        # deficit view ties the two control loops together for the
        # operator (`ray_tpu status`, last_status asserts in tests).
        serve_deficits = {
            key: {
                "target": rec.get("target", 0),
                "replicas": rec.get("replicas", 0),
                "missing": rec.get("target", 0) - rec.get("replicas", 0),
            }
            for key, rec in (status.get("serve_autoscale") or {}).items()
            if rec.get("target", 0) > rec.get("replicas", 0)
        }

        self.last_status = {
            "demand": demand,
            "added": to_add,
            "tracked": {
                pid: t.node_type for pid, t in self._tracked.items()
            },
            "draining": {nid: dict(d) for nid, d in draining.items()},
            "chronic_stragglers": chronic,
            "serve_deficits": serve_deficits,
        }
        return self.last_status
