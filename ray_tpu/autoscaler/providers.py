"""Node providers: how the autoscaler actually adds/removes capacity.

Reference: autoscaler node providers (aws/gcp/kuberay under
python/ray/autoscaler/_private and v2/instance_manager); tests use a fake
provider (reference: cluster_utils.py:26 AutoscalingCluster). Here the
fake provider starts real NodeManager daemons in-process — the same
multi-raylet-on-one-host strategy the reference test suite uses.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any


class NodeProvider:
    """ABC: create/terminate cluster nodes of a given node type."""

    def create_node(self, node_type: str, resources: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def terminate_nodes(self, provider_node_ids: "list[str]") -> None:
        """Terminate a batch in one shot — the autoscaler reaps a
        fully-drained slice through this so providers with a unit-level
        API (queued resources, MIG deleteInstances) tear the slice down
        as ONE call. Default: per-node teardown."""
        for pid in provider_node_ids:
            self.terminate_node(pid)

    def non_terminated_nodes(self) -> dict[str, str]:
        """provider_node_id → node_type."""
        raise NotImplementedError

    def runtime_node_id(self, provider_node_id: str) -> str | None:
        """Map a provider node to the runtime node_id it registered as."""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Launch NodeManager daemons inside the driver's runtime loop."""

    def __init__(self):
        from ray_tpu import api as core_api

        self._rt = core_api._runtime
        self._nodes: dict[str, dict] = {}  # pid → {node, type}

    def create_node(self, node_type: str, resources: dict) -> str:
        from ray_tpu.runtime.node import NodeManager

        rt = self._rt

        async def launch():
            node = NodeManager(
                rt.core.head_addr,
                rt.core.store.dir.as_posix(),
                resources=dict(resources),
            )
            await node.start()
            return node

        node = self._rt.run(launch())
        pid = f"fake-{uuid.uuid4().hex[:8]}"
        self._nodes[pid] = {"node": node, "type": node_type}
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        rec = self._nodes.pop(provider_node_id, None)
        if rec is None:
            return
        self._rt.run(rec["node"].stop())

    def non_terminated_nodes(self) -> dict[str, str]:
        return {pid: rec["type"] for pid, rec in self._nodes.items()}

    def runtime_node_id(self, provider_node_id: str) -> str | None:
        rec = self._nodes.get(provider_node_id)
        return rec["node"].node_id if rec else None


# The real GKE/Cloud-TPU provider lives in its own module (REST
# transport, operation polling, fixtures); re-exported here for the
# historical import path. (The package __init__ imports this module
# eagerly, so a lazy shim would buy nothing.)
from ray_tpu.autoscaler.gcp import GkeTpuNodeProvider  # noqa: E402,F401
