"""Autoscaler (v2-style): poll demand → bin-pack → drive a node provider.

Reference architecture: python/ray/autoscaler/v2/autoscaler.py:50 polls
GcsAutoscalerStateManager, v2/scheduler.py bin-packs pending demand onto
node types, InstanceManager (v2/instance_manager/instance_manager.py:29)
drives cloud providers. TPU twist: a slice is the atomic unit — a
node type models a whole slice (all its hosts come and go together).
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, NodeTypeConfig
from ray_tpu.autoscaler.providers import (
    FakeNodeProvider,
    GkeTpuNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.scheduler import fit_demand

__all__ = [
    "Autoscaler",
    "FakeNodeProvider",
    "GkeTpuNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "fit_demand",
]
