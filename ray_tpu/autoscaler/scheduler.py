"""Demand → node-type bin-packing (reference: autoscaler/v2/scheduler.py
ResourceDemandScheduler — first-fit-decreasing over node type shapes).
"""

from __future__ import annotations


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0) >= v for k, v in req.items())


def _take(avail: dict, req: dict) -> None:
    for k, v in req.items():
        avail[k] = avail.get(k, 0) - v


def fit_demand(
    demand: list[dict],
    node_types: dict[str, dict],
    existing_counts: dict[str, int],
    free_by_node: list[dict],
) -> dict[str, int]:
    """Return {node_type: count} of nodes to add so `demand` fits.

    `node_types`: {name: {"resources": {...}, "max_workers": int}}.
    `free_by_node`: currently-available resources per live node (demand
    that fits existing headroom needs no new nodes).
    """
    # Largest requests first: better packing, fewer nodes.
    pending = sorted(
        (dict(d) for d in demand),
        key=lambda d: -sum(d.values()),
    )
    headroom = [dict(f) for f in free_by_node]
    to_add: dict[str, int] = {}
    virtual: list[dict] = []  # capacity of nodes we've decided to add

    for req in pending:
        placed = False
        for avail in headroom + virtual:
            if _fits(avail, req):
                _take(avail, req)
                placed = True
                break
        if placed:
            continue
        # Pick the cheapest (smallest total capacity) node type that can
        # ever fit the request, respecting max_workers.
        candidates = []
        for name, cfg in node_types.items():
            if not _fits(cfg["resources"], req):
                continue
            used = existing_counts.get(name, 0) + to_add.get(name, 0)
            if used >= cfg.get("max_workers", 2**31):
                continue
            candidates.append((sum(cfg["resources"].values()), name))
        if not candidates:
            continue  # permanently infeasible: surface via status, not nodes
        _, chosen = min(candidates)
        to_add[chosen] = to_add.get(chosen, 0) + 1
        cap = dict(node_types[chosen]["resources"])
        _take(cap, req)
        virtual.append(cap)
    return to_add
