"""Job submission: run driver scripts on the cluster and track them.

Reference: python/ray/dashboard/modules/job/job_manager.py:62 — REST
submit spawns a per-job supervisor actor that execs the entrypoint as a
subprocess, tracks status in GCS, and serves logs. Same architecture
here: `_JobSupervisor` is a detached-ish actor that Popens the entrypoint
with the cluster address in its env; job records live in the head KV
under "job:<id>".
"""

from __future__ import annotations

import json
import os
import time
import uuid

import ray_tpu
from ray_tpu import api as core_api

_JOB_KEY = "job:"


class _JobSupervisor:
    """One per job; owns the entrypoint subprocess."""

    def __init__(self, job_id: str, entrypoint: str, env: dict, log_path: str):
        import subprocess

        self.job_id = job_id
        self.log_path = log_path
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        self._log_file = open(log_path, "wb")
        full_env = {**os.environ, **env}
        self.proc = subprocess.Popen(
            entrypoint,
            shell=True,
            stdout=self._log_file,
            stderr=subprocess.STDOUT,
            env=full_env,
            start_new_session=True,
        )
        self.start_time = time.time()

    def poll(self) -> dict:
        rc = self.proc.poll()
        if rc is None:
            status = "RUNNING"
        elif rc == 0:
            status = "SUCCEEDED"
        else:
            status = "FAILED"
        return {
            "job_id": self.job_id,
            "status": status,
            "returncode": rc,
            "start_time": self.start_time,
        }

    def logs(self) -> str:
        self._log_file.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode("utf-8", "replace")
        except FileNotFoundError:
            return ""

    def stop_job(self) -> bool:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            # tpulint: allow(broad-except reason=the child ignored SIGTERM past the grace window; escalating to SIGKILL IS the handling)
            except Exception:  # noqa: BLE001
                self.proc.kill()
            return True
        return False


def _kv_put(key: str, value: dict):
    rt = core_api._runtime

    async def go():
        await rt.core.head.call(
            "kv_put", key=key, value=json.dumps(value).encode(), overwrite=True
        )

    rt.run(go())


def _kv_get(key: str) -> dict | None:
    rt = core_api._runtime

    async def go():
        return await rt.core.head.call("kv_get", key=key)

    reply = rt.run(go())
    if not reply["ok"]:
        return None
    return json.loads(reply["value"].decode())


def _kv_keys(prefix: str) -> list[str]:
    rt = core_api._runtime

    async def go():
        return await rt.core.head.call("kv_keys", prefix=prefix)

    return rt.run(go())["keys"]


class JobSubmissionClient:
    """Reference: ray.job_submission.JobSubmissionClient (sdk.py)."""

    def __init__(self):
        self._supervisors: dict[str, object] = {}

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: str | None = None,
        runtime_env: dict | None = None,
    ) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        env_vars = dict((runtime_env or {}).get("env_vars", {}))
        # The entrypoint driver connects back to THIS cluster.
        head_addr = core_api._runtime.core.head_addr
        env_vars.setdefault("RAY_TPU_ADDRESS", head_addr)
        log_path = os.path.join(
            "/tmp", "ray_tpu_jobs", f"{job_id}.log"
        )
        supervisor_cls = ray_tpu.remote(_JobSupervisor)
        # Supervisors idle-wait on a subprocess; a fractional CPU keeps
        # many concurrent jobs from starving real work (reference: the
        # supervisor actor is scheduled with 0 CPUs, job_manager.py).
        sup = supervisor_cls.options(
            name=f"_job_supervisor:{job_id}", num_cpus=0.01
        ).remote(job_id, entrypoint, env_vars, log_path)
        self._supervisors[job_id] = sup
        record = {
            "job_id": job_id,
            "entrypoint": entrypoint,
            "status": "RUNNING",
            "submission_time": time.time(),
        }
        _kv_put(_JOB_KEY + job_id, record)
        return job_id

    def _sup(self, job_id: str):
        sup = self._supervisors.get(job_id)
        if sup is None:
            sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
            self._supervisors[job_id] = sup
        return sup

    def get_job_status(self, job_id: str) -> str:
        try:
            info = ray_tpu.get(self._sup(job_id).poll.remote())
        # tpulint: allow(broad-except reason=a dead supervisor actor means the job reached a terminal state; the KV record below is the authoritative fallback answer)
        except Exception:  # noqa: BLE001 - supervisor gone → terminal state
            rec = _kv_get(_JOB_KEY + job_id)
            return rec["status"] if rec else "UNKNOWN"
        rec = _kv_get(_JOB_KEY + job_id) or {"job_id": job_id}
        if rec.get("status") != info["status"]:
            rec.update(status=info["status"], returncode=info["returncode"])
            _kv_put(_JOB_KEY + job_id, rec)
        return info["status"]

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._sup(job_id).logs.remote())

    def stop_job(self, job_id: str) -> bool:
        stopped = ray_tpu.get(self._sup(job_id).stop_job.remote())
        if stopped:
            rec = _kv_get(_JOB_KEY + job_id) or {"job_id": job_id}
            rec["status"] = "STOPPED"
            _kv_put(_JOB_KEY + job_id, rec)
        return stopped

    def list_jobs(self) -> list[dict]:
        out = []
        for key in _kv_keys(_JOB_KEY):
            rec = _kv_get(key)
            if rec:
                # Refresh live status where the supervisor still answers.
                if rec.get("status") == "RUNNING":
                    rec["status"] = self.get_job_status(rec["job_id"])
                out.append(rec)
        return out

    def delete_job(self, job_id: str) -> bool:
        """Kill the supervisor and drop the record (terminal jobs only)."""
        status = self.get_job_status(job_id)
        if status == "RUNNING":
            raise RuntimeError("stop the job before deleting it")
        try:
            ray_tpu.kill(self._sup(job_id))
        # tpulint: allow(broad-except reason=deleting a terminal job; a supervisor that is already gone is the desired end state)
        except Exception:  # noqa: BLE001 - already gone
            pass
        self._supervisors.pop(job_id, None)
        rt = core_api._runtime

        async def go():
            await rt.core.head.call("kv_del", key=_JOB_KEY + job_id)

        rt.run(go())
        return True

    def wait_until_finish(
        self, job_id: str, timeout: float = 120.0
    ) -> str:
        deadline = time.time() + timeout
        while True:
            status = self.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            if time.time() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {status} after {timeout}s"
                )
            time.sleep(0.5)
