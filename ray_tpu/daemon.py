"""Daemonized cluster processes behind `ray_tpu start` / `ray_tpu stop`
(reference: `ray start --head` / `--address` scripts/scripts.py:682, which
exec the gcs_server and raylet binaries; here the head service and node
manager are asyncio services hosted by this module's entry point).

Layout of a session directory (one per host, default
/tmp/ray_tpu_cluster):

    head.addr      advertised head address (written atomically when up)
    head.journal   durable head state (KV/actors/PGs) — enables head
                   restart with state intact (see runtime/head_storage)
    *.pid          one per daemonized process, consumed by `stop`
    logs/*.log     daemon stdout/stderr

`python -m ray_tpu.daemon head|node ...` runs a process in the
foreground; the CLI (scripts.py) forks it into the background with
start_new_session and tracks the pid.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import json
import os
import signal
import sys
import tempfile

logger = logging.getLogger("ray_tpu.daemon")

DEFAULT_SESSION_DIR = os.path.join(
    tempfile.gettempdir(), "ray_tpu_cluster"
)


def _write_atomic(path: str, text: str) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "w") as f:
        f.write(text)
    os.rename(tmp, path)


def _resources(args) -> dict:
    from ray_tpu.runtime.node import detect_resources

    total = detect_resources()
    if args.num_cpus is not None:
        total["CPU"] = float(args.num_cpus)
    if args.resources:
        total.update(json.loads(args.resources))
    return total


async def _serve_until_signal(stoppables, node=None) -> None:
    """Run until SIGTERM/SIGINT, then stop services newest-first.

    With a local ``node``, SIGTERM is treated as a preemption notice
    (GCE delivers ~30s of ACPI-shutdown warning as SIGTERM): the node
    self-reports DRAINING to the head — so schedulers divert and train
    workers get their emergency-checkpoint window — and then keeps
    serving for RAY_TPU_DRAIN_SIGTERM_LINGER_S (default 0: notify and
    stop, which keeps `ray_tpu stop` prompt). A second signal always
    cuts the linger short."""
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if node is not None and not node.draining:
        from ray_tpu._private import config

        try:
            await asyncio.wait_for(node.self_drain("sigterm"), 2.0)
        except Exception:  # noqa: BLE001 - head may already be gone
            logger.debug("sigterm self-drain notify failed", exc_info=True)
        linger = config.get("DRAIN_SIGTERM_LINGER_S")
        if linger > 0:
            stop.clear()
            try:
                await asyncio.wait_for(stop.wait(), linger)
            except asyncio.TimeoutError:
                pass
    for s in reversed(stoppables):
        try:
            await s.stop()
        except Exception:  # noqa: BLE001 - best-effort teardown
            logger.debug("daemon component stop failed", exc_info=True)


_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def resolve_token(
    session_dir: str,
    *,
    explicit: str | None = None,
    no_auth: bool = False,
    is_head: bool = False,
    host: str = "127.0.0.1",
    warn=print,
) -> str:
    """The ONE token-resolution rule, shared by the CLI and the daemon.

    Default-ON auth (reference: token auth
    authentication_token_validator.h:26): explicit flag > env/config >
    (head: generate; node: session-dir file). Returns "" only under
    --no-auth, warning loudly when that combines with a routable bind
    address (the RPC layer deserializes pickle between authenticated
    peers — an open port is code execution)."""
    import secrets

    from ray_tpu._private import config

    token = explicit or config.get("AUTH_TOKEN")
    if no_auth:
        token = ""
    elif not token:
        # Reuse the session token if one exists — a crash-restarted
        # head must NOT rotate it, or every surviving node and driver
        # holding the old token is locked out.
        token_path = os.path.join(session_dir, "auth.token")
        if os.path.exists(token_path):
            token = open(token_path).read().strip()
        if not token and is_head:
            token = secrets.token_hex(16)
    if not token and host not in _LOOPBACK:
        warn(
            f"WARNING: binding {host} with auth disabled — any host "
            "with network reach gets code execution. Set "
            "RAY_TPU_AUTH_TOKEN or drop --no-auth."
        )
    return token


def _setup_security(args, session_dir: str, is_head: bool) -> str:
    """Resolve the auth token + TLS material and install them in config
    (set_system_config also exports to os.environ, which is how spawned
    workers inherit them). Returns the resolved token ("" = auth off)."""
    from ray_tpu._private import config

    token_path = os.path.join(session_dir, "auth.token")
    token = resolve_token(
        session_dir,
        no_auth=getattr(args, "no_auth", False),
        is_head=is_head,
        host=args.host,
        warn=lambda msg: print(msg, flush=True),
    )
    overrides = {"AUTH_TOKEN": token}
    if is_head:
        if token:
            fd = os.open(
                token_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600
            )
            with os.fdopen(fd, "w") as f:
                f.write(token)
        else:
            # A stale token from a previous authed cluster would poison
            # joins and CLI connects to this no-auth one.
            try:
                os.unlink(token_path)
            except OSError:
                pass
    if getattr(args, "tls", False):
        # Operator-provided material (env/config) wins; otherwise the
        # session dir. Only the head may generate — every other host
        # must receive a COPY of both files (one shared cert is the
        # cluster's identity; clients pin it).
        cert = config.get("TLS_CERT") or os.path.join(session_dir, "tls.crt")
        key = config.get("TLS_KEY") or os.path.join(session_dir, "tls.key")
        if not (os.path.exists(cert) and os.path.exists(key)):
            if is_head:
                from ray_tpu._private.tls_utils import generate_self_signed

                generate_self_signed(cert, key)
            else:
                raise SystemExit(
                    f"--tls: no cert/key at {cert} / {key}; copy "
                    "tls.crt AND tls.key from the head's session dir "
                    "(or set RAY_TPU_TLS_CERT / RAY_TPU_TLS_KEY)"
                )
        overrides["TLS_CERT"] = cert
        overrides["TLS_KEY"] = key
    elif config.get("TLS_CERT"):
        overrides["TLS_CERT"] = config.get("TLS_CERT")
        overrides["TLS_KEY"] = config.get("TLS_KEY")
    config.set_system_config(overrides)
    return token


async def _run_head(args) -> None:
    from ray_tpu._private import config
    from ray_tpu.runtime.head import HeadService
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.runtime.object_store import default_store_dir

    session_dir = args.session_dir
    os.makedirs(session_dir, exist_ok=True)
    token = _setup_security(args, session_dir, is_head=True)
    # HEAD_JOURNAL (including the documented 'off') wins over the
    # session default.
    journal = config.get("HEAD_JOURNAL") or os.path.join(
        session_dir, "head.journal"
    )
    head = HeadService(journal_path=journal)
    addr = await head.start(host=args.host, port=args.port)
    if config.get("HEAD_GC_FREEZE"):
        # Tail-latency discipline for the dedicated head process: after
        # boot + journal restore, move everything live so far into the
        # permanent generation (gen2 passes then scan only post-boot
        # garbage, not every module object) and raise gen0 so a
        # telemetry flood's allocation churn doesn't cascade collector
        # passes into the RPC dispatch path. Cyclic garbage still gets
        # collected — just on an amortized cadence.
        import gc

        gc.collect()
        gc.freeze()
        gc.set_threshold(50_000, 25, 25)
    nice_target = config.get("HEAD_NICE")
    if nice_target:
        # On a shared host the head competes with data-plane work for
        # CPU; when both are saturated, every control RPC waits a full
        # scheduler rotation behind its neighbours. Elevated priority
        # keeps the control plane responsive — best effort (negative
        # values need CAP_SYS_NICE).
        try:
            os.setpriority(os.PRIO_PROCESS, 0, nice_target)
        except OSError as e:
            logger.warning("HEAD_NICE=%s not applied: %s",
                           nice_target, e)
    # Workers this node spawns need the journal off (only the head
    # process owns it) but the cluster address on.
    config.set_system_config({"ADDRESS": addr})

    stoppables = [head]
    node = None
    if not args.head_only:
        node = NodeManager(
            head_addr=addr,
            store_dir=default_store_dir(f"cli-{os.getpid()}"),
            resources=_resources(args),
        )
        await node.start(host=args.host)
        stoppables.append(node)

    _write_atomic(os.path.join(session_dir, "head.addr"), addr)
    try:
        # Local artifact (environment/version info; the driver-side
        # /api/usage endpoint carries the live cluster view); the POST
        # fires only when the operator set RAY_TPU_USAGE_REPORT_URL.
        from ray_tpu._private import usage

        usage.write_usage_file(session_dir)
        import threading

        threading.Thread(
            target=usage.report_if_enabled, daemon=True
        ).start()
    except Exception:  # noqa: BLE001 - observability must not block boot
        logger.debug("usage reporting setup failed", exc_info=True)
    # The daemon's stdout lands in a log file under the session dir —
    # never print the token itself here (the 0600 token file is the
    # secret's only resting place; the CLI prints the join command to
    # the operator's terminal).
    print(f"head up at {addr}", flush=True)
    tls_note = " --tls (copy tls.crt AND tls.key over first)" if getattr(
        args, "tls", False
    ) else ""
    env_prefix = "RAY_TPU_AUTH_TOKEN=<token> " if token else ""
    print(
        f"join from other hosts:  {env_prefix}python -m ray_tpu.scripts "
        f"start --address {addr}{tls_note}",
        flush=True,
    )
    if token:
        print(
            f"auth token (the <token> above) is in "
            f"{session_dir}/auth.token",
            flush=True,
        )
    await _serve_until_signal(stoppables, node=node)


async def _run_node(args) -> None:
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.runtime.object_store import default_store_dir

    _setup_security(args, args.session_dir, is_head=False)
    node = NodeManager(
        head_addr=args.address,
        store_dir=default_store_dir(f"cli-{os.getpid()}"),
        resources=_resources(args),
    )
    addr = await node.start(host=args.host)
    print(f"node up at {addr} (head {args.address})", flush=True)
    await _serve_until_signal([node], node=node)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu.daemon")
    sub = p.add_subparsers(dest="role", required=True)
    for role in ("head", "node"):
        sp = sub.add_parser(role)
        sp.add_argument("--host", default="127.0.0.1")
        sp.add_argument("--num-cpus", type=float, default=None)
        sp.add_argument("--resources", default=None, help="JSON dict")
        sp.add_argument("--session-dir", default=DEFAULT_SESSION_DIR)
        sp.add_argument(
            "--no-auth",
            action="store_true",
            help="disable the connection token (loopback dev only)",
        )
        sp.add_argument(
            "--tls",
            action="store_true",
            help="encrypt cluster RPC (head generates a self-signed "
            "cert in the session dir; nodes need a copy of tls.crt)",
        )
        if role == "head":
            sp.add_argument("--port", type=int, default=0)
            sp.add_argument(
                "--head-only",
                action="store_true",
                help="run the head service without a local node",
            )
        else:
            sp.add_argument("--address", required=True)
    args = p.parse_args(argv)
    runner = _run_head if args.role == "head" else _run_node
    asyncio.run(runner(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
