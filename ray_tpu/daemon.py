"""Daemonized cluster processes behind `ray_tpu start` / `ray_tpu stop`
(reference: `ray start --head` / `--address` scripts/scripts.py:682, which
exec the gcs_server and raylet binaries; here the head service and node
manager are asyncio services hosted by this module's entry point).

Layout of a session directory (one per host, default
/tmp/ray_tpu_cluster):

    head.addr      advertised head address (written atomically when up)
    head.journal   durable head state (KV/actors/PGs) — enables head
                   restart with state intact (see runtime/head_storage)
    *.pid          one per daemonized process, consumed by `stop`
    logs/*.log     daemon stdout/stderr

`python -m ray_tpu.daemon head|node ...` runs a process in the
foreground; the CLI (scripts.py) forks it into the background with
start_new_session and tracks the pid.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import tempfile

DEFAULT_SESSION_DIR = os.path.join(
    tempfile.gettempdir(), "ray_tpu_cluster"
)


def _write_atomic(path: str, text: str) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    with os.fdopen(fd, "w") as f:
        f.write(text)
    os.rename(tmp, path)


def _resources(args) -> dict:
    from ray_tpu.runtime.node import detect_resources

    total = detect_resources()
    if args.num_cpus is not None:
        total["CPU"] = float(args.num_cpus)
    if args.resources:
        total.update(json.loads(args.resources))
    return total


async def _serve_until_signal(stoppables) -> None:
    """Run until SIGTERM/SIGINT, then stop services newest-first."""
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    for s in reversed(stoppables):
        try:
            await s.stop()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass


async def _run_head(args) -> None:
    from ray_tpu._private import config
    from ray_tpu.runtime.head import HeadService
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.runtime.object_store import default_store_dir

    session_dir = args.session_dir
    os.makedirs(session_dir, exist_ok=True)
    journal = os.path.join(session_dir, "head.journal")
    head = HeadService(journal_path=journal)
    addr = await head.start(host=args.host, port=args.port)
    # Workers this node spawns need the journal off (only the head
    # process owns it) but the cluster address on.
    config.set_system_config({"ADDRESS": addr})

    stoppables = [head]
    if not args.head_only:
        node = NodeManager(
            head_addr=addr,
            store_dir=default_store_dir(f"cli-{os.getpid()}"),
            resources=_resources(args),
        )
        await node.start(host=args.host)
        stoppables.append(node)

    _write_atomic(os.path.join(session_dir, "head.addr"), addr)
    print(f"head up at {addr}", flush=True)
    print(
        f"join from other hosts:  python -m ray_tpu.scripts start "
        f"--address {addr}",
        flush=True,
    )
    await _serve_until_signal(stoppables)


async def _run_node(args) -> None:
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.runtime.object_store import default_store_dir

    node = NodeManager(
        head_addr=args.address,
        store_dir=default_store_dir(f"cli-{os.getpid()}"),
        resources=_resources(args),
    )
    addr = await node.start(host=args.host)
    print(f"node up at {addr} (head {args.address})", flush=True)
    await _serve_until_signal([node])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu.daemon")
    sub = p.add_subparsers(dest="role", required=True)
    for role in ("head", "node"):
        sp = sub.add_parser(role)
        sp.add_argument("--host", default="127.0.0.1")
        sp.add_argument("--num-cpus", type=float, default=None)
        sp.add_argument("--resources", default=None, help="JSON dict")
        sp.add_argument("--session-dir", default=DEFAULT_SESSION_DIR)
        if role == "head":
            sp.add_argument("--port", type=int, default=0)
            sp.add_argument(
                "--head-only",
                action="store_true",
                help="run the head service without a local node",
            )
        else:
            sp.add_argument("--address", required=True)
    args = p.parse_args(argv)
    runner = _run_head if args.role == "head" else _run_node
    asyncio.run(runner(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
