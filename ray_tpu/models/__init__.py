"""Model zoo: flagship Llama-3-style decoder (GQA + SwiGLU + RoPE), MoE
(ray_tpu.models.moe), and ResNet vision models (ray_tpu.models.resnet),
plus smaller configs for tests and single-chip benchmarks."""

from ray_tpu.models.llama import (
    LlamaConfig,
    PRESETS,
    forward,
    init_params,
    param_logical_axes,
)
from ray_tpu.models.resnet import ResNetConfig
from ray_tpu.models.resnet import PRESETS as RESNET_PRESETS

__all__ = [
    "LlamaConfig",
    "PRESETS",
    "RESNET_PRESETS",
    "ResNetConfig",
    "forward",
    "init_params",
    "param_logical_axes",
]
