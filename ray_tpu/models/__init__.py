"""Model zoo: flagship Llama-3-style decoder (GQA + SwiGLU + RoPE), plus
smaller configs for tests and single-chip benchmarks."""

from ray_tpu.models.llama import (
    LlamaConfig,
    PRESETS,
    forward,
    init_params,
    param_logical_axes,
)

__all__ = [
    "LlamaConfig",
    "PRESETS",
    "forward",
    "init_params",
    "param_logical_axes",
]
