"""Flagship model: Llama-3-style decoder-only transformer in pure JAX.

GQA attention + RoPE + SwiGLU + RMSNorm, parameters stored fp32 and cast to
bf16 at use (mixed precision), layers stacked on a leading dim and executed
with `lax.scan` (+ optional rematerialization) so XLA compiles one layer
body regardless of depth — static shapes, no Python-level per-layer loop.

Every parameter carries logical axes (see ray_tpu.parallel.sharding) so a
single rule table gives DP/FSDP/TP/SP shardings under pjit. This is the
model behind BASELINE.json configs 2–3 (the reference's equivalent role is
filled by user torch code under TorchTrainer; SURVEY.md section 2.4).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ray_tpu.ops.attention import causal_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.parallel.sharding import constrain

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.bfloat16
    # Remat policy for the scanned layer body:
    #   "none"  keep all activations (fastest, most memory)
    #   "full"  recompute everything in backward (least memory)
    #   "dots"  save matmul outputs, recompute elementwise (middle ground;
    #           jax dots_with_no_batch_dims_saveable)
    remat: str = "full"
    # "dense" | "ring" | "ulysses": attention strategy. ring/ulysses need a
    # mesh with sp>1 (built by ray_tpu.train.step.jit_train_step).
    attn_impl: str = "dense"
    # Embedding lookup strategy:
    #   "gather"  table[tokens] — fastest on a single chip
    #   "onehot"  one_hot(tokens) @ table — a matmul, which the SPMD
    #             partitioner handles cleanly when the table is sharded
    #             (vocab on tp, embed on fsdp); a sharded gather instead
    #             triggers "involuntary full rematerialization" (the
    #             compiler replicates the whole activation to reshard)
    #   "auto"    onehot when >1 device is visible, else gather
    embed_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        attn = d * (self.n_heads * self.head_dim) * 2 + d * (
            self.n_kv_heads * self.head_dim
        ) * 2
        mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        return self.n_layers * per_layer + 2 * v * d + d

    def flops_per_token(self, seq: int) -> float:
        """Training (fwd+bwd) FLOPs per token: 6*N_matmul + attention term."""
        d, v = self.d_model, self.vocab_size
        matmul_params = self.num_params() - v * d  # exclude embedding lookup
        attn_flops = 12 * self.n_layers * d * seq  # 6 * 2 * L * d * s
        return 6.0 * matmul_params + attn_flops


PRESETS: dict[str, LlamaConfig] = {
    # CPU-test scale.
    "tiny": LlamaConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=256, dtype=jnp.float32, remat="none",
    ),
    # Single-chip graft-entry scale (~125M).
    "mini": LlamaConfig(
        vocab_size=32768, d_model=768, n_layers=12, n_heads=12, n_kv_heads=4,
        d_ff=2048, max_seq=2048,
    ),
    # Single-chip benchmark scale (~430M). head_dim 128 (Llama-3's) over
    # 64: the MXU is 128 wide, so D=64 attention runs both kernel
    # matmuls at half width — same parameter count (h·D and hkv·D
    # unchanged), ~40% faster attention.
    # remat="flash_qkv": keep the flash kernel's residuals (out+lse)
    # AND its q/k/v inputs across the remat boundary — the backward
    # replay skips the whole attention forward (kernel + projections +
    # RoPE). ~97 MB/layer of residuals; measured +10% step throughput
    # over full remat on v5e (PROFILE_r04.md).
    "bench": LlamaConfig(
        vocab_size=32768, d_model=1024, n_layers=24, n_heads=8, n_kv_heads=4,
        d_ff=4096, max_seq=2048, remat="flash_qkv",
    ),
    # Llama-3-8B (BASELINE.json config 3).
    "llama3_8b": LlamaConfig(),
}


def param_logical_axes(cfg: LlamaConfig) -> Params:
    """Pytree of logical-axis tuples, mirroring init_params' structure."""
    del cfg
    return {
        "tok_emb": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialize fp32 parameters (truncated-normal, 1/sqrt(fan_in))."""
    d, f = cfg.d_model, cfg.d_ff
    hq = cfg.n_heads * cfg.head_dim
    hkv = cfg.n_kv_heads * cfg.head_dim
    L = cfg.n_layers
    keys = jax.random.split(key, 9)

    def w(k, shape, fan_in):
        return (
            jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32)
            * fan_in**-0.5
        )

    return {
        "tok_emb": w(keys[0], (cfg.vocab_size, d), d),
        "blocks": {
            "attn_norm": jnp.zeros((L, d), jnp.float32),
            "wq": w(keys[1], (L, d, hq), d),
            "wk": w(keys[2], (L, d, hkv), d),
            "wv": w(keys[3], (L, d, hkv), d),
            "wo": w(keys[4], (L, hq, d), hq),
            "mlp_norm": jnp.zeros((L, d), jnp.float32),
            "w_gate": w(keys[5], (L, d, f), d),
            "w_up": w(keys[6], (L, d, f), d),
            "w_down": w(keys[7], (L, f, d), f),
        },
        "final_norm": jnp.zeros((d,), jnp.float32),
        "lm_head": w(keys[8], (d, cfg.vocab_size), d),
    }


def _embed(table: jnp.ndarray, tokens: jnp.ndarray, cfg: LlamaConfig):
    """Token embedding lookup. Under a sharded mesh the lookup runs as a
    one-hot matmul: a gather from a (vocab=tp, embed=fsdp)-sharded table
    forces the SPMD partitioner into an involuntary full
    rematerialization (replicate-then-reshard) on the activation, while
    the matmul contraction partitions natively (and rides the MXU). On a
    single chip the plain gather is cheaper."""
    table = table.astype(cfg.dtype)
    impl = cfg.embed_impl
    if impl == "auto":
        impl = "onehot" if jax.device_count() > 1 else "gather"
    if impl == "gather":
        return table[tokens]
    if impl != "onehot":
        raise ValueError(f"unknown embed_impl {cfg.embed_impl!r}")
    onehot = jax.nn.one_hot(tokens, table.shape[0], dtype=table.dtype)
    return onehot @ table


AttnFn = Callable[..., jnp.ndarray]


FfnFn = Callable[..., tuple[jnp.ndarray, jnp.ndarray]]


def _dense_ffn(h: jnp.ndarray, p: Params, cfg: LlamaConfig):
    dt = cfg.dtype
    gate = jax.nn.silu(h @ p["w_gate"].astype(dt))
    up = h @ p["w_up"].astype(dt)
    return (gate * up) @ p["w_down"].astype(dt), jnp.float32(0.0)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _int8_ckpt(x: jnp.ndarray, name: str) -> jnp.ndarray:
    """Quantize-through-checkpoint: the value crossing the remat
    boundary is int8 + a per-row fp32 scale (tagged for
    save_only_these_names), halving the residual HBM of a saved bf16
    activation. A custom_vjp (straight-through cotangent) rather than
    the x + stop_gradient(dq - x) identity trick: that formulation
    keeps the UN-quantized x structurally live in the primal output,
    so the backward replay would re-run the producing matmul anyway —
    the primal here depends only on (q, scale), which the policy
    saves."""
    scale = (
        jnp.max(jnp.abs(x), axis=-1, keepdims=True).astype(jnp.float32)
        / 127.0
        + 1e-12
    )
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    q = checkpoint_name(q, name)
    scale = checkpoint_name(scale, name + "_scale")
    return (q.astype(jnp.float32) * scale).astype(x.dtype)


def _int8_ckpt_fwd(x, name):
    return _int8_ckpt(x, name), ()


def _int8_ckpt_bwd(name, _res, g):
    return (g,)  # straight-through: quantization grad is identity


_int8_ckpt.defvjp(_int8_ckpt_fwd, _int8_ckpt_bwd)


def _dense_ffn_save(h: jnp.ndarray, p: Params, cfg: LlamaConfig):
    """FFN with bf16-tagged gate-pre/up activations (the unquantized
    sibling of :func:`_dense_ffn_q8`)."""
    dt = cfg.dtype
    gate_pre = checkpoint_name(h @ p["w_gate"].astype(dt), "ffn_gate")
    up = checkpoint_name(h @ p["w_up"].astype(dt), "ffn_up")
    return (jax.nn.silu(gate_pre) * up) @ p["w_down"].astype(dt), (
        jnp.float32(0.0)
    )


def _dense_ffn_q8(h: jnp.ndarray, p: Params, cfg: LlamaConfig):
    """FFN whose gate-pre/up activations cross the remat boundary as
    int8: with their names pinned by the checkpoint policy, the
    backward replay skips BOTH [B,S,d]x[d,ff] forward matmuls
    (PROFILE_r04 'int8 saved FFN activations' lever)."""
    dt = cfg.dtype
    gate_pre = _int8_ckpt(h @ p["w_gate"].astype(dt), "ffn_gate")
    up = _int8_ckpt(h @ p["w_up"].astype(dt), "ffn_up")
    return (jax.nn.silu(gate_pre) * up) @ p["w_down"].astype(dt), (
        jnp.float32(0.0)
    )


def _block(
    x: jnp.ndarray,
    p: Params,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    cfg: LlamaConfig,
    attn_fn: AttnFn,
    ffn_fn: FfnFn,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Pre-norm attention + FFN sublayers; ffn_fn returns (out, aux) so
    MoE layers (ray_tpu.models.moe) reuse this block unchanged."""
    b, s, d = x.shape
    dt = cfg.dtype

    x = constrain(x, "batch", "act_seq", "act_embed")
    h = rms_norm(x, p["attn_norm"])
    q = (h @ p["wq"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (h @ p["wk"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = (h @ p["wv"].astype(dt)).reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = checkpoint_name(attn_fn(q, k, v), "attn_out")
    x = x + attn.reshape(b, s, -1) @ p["wo"].astype(dt)

    h = rms_norm(x, p["mlp_norm"])
    ffn_out, aux = ffn_fn(h, p, cfg)
    return x + ffn_out, aux


def forward_with_aux(
    params: Params,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    attn_fn: AttnFn | None = None,
    ffn_fn: FfnFn | None = None,
    return_hidden: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [B, S] int32 → (logits [B, S, V] fp32, summed aux loss).

    With ``return_hidden`` the final-norm hidden states [B, S, d] come
    back instead of logits — the chunked-CE loss projects them to the
    vocabulary a slice at a time so the full [B, S, V] logits (and their
    gradient) never materialize.
    """
    attn_fn = attn_fn or causal_attention
    ffn_fn = ffn_fn or _dense_ffn
    seq = tokens.shape[1]
    cos, sin = rope_frequencies(cfg.head_dim, seq, cfg.rope_theta)

    x = _embed(params["tok_emb"], tokens, cfg)
    x = constrain(x, "batch", "act_seq", "act_embed")

    body = partial(_block, cos=cos, sin=sin, cfg=cfg, attn_fn=attn_fn,
                   ffn_fn=ffn_fn)
    if cfg.remat == "full":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.nothing_saveable
        )
    elif cfg.remat == "attn":
        # Save ONLY the attention outputs: the backward pass skips the
        # flash-kernel forward recompute (the most expensive part of the
        # layer to re-run) at a cost of one [B, S, H, D] bf16 residual
        # per layer — the standard selective-remat sweet spot for long
        # sequences.
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out"
            ),
        )
    elif cfg.remat == "flash":
        # Save the flash kernel's OWN residuals (its output + per-row
        # logsumexp, tagged inside the kernel's custom-vjp fwd): the
        # backward replay then rebuilds only norms/projections/FFN and
        # never re-runs the forward attention kernel — the expensive,
        # O(S^2) part of the recompute. Costs ~one [B,S,H,D] bf16 + one
        # [B,H,S] fp32 residual per layer; everything else stays fully
        # rematerialized.
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"
            ),
        )
    elif cfg.remat == "flash_qkv":
        # "flash" plus the attention INPUTS: the replay also skips the
        # qkv projections + RoPE. ~2x the residual memory of "flash".
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse", "flash_qkv"
            ),
        )
    elif cfg.remat == "flash_qkv_ffn":
        # bf16-saved FFN activations (no quantization): same skipped
        # recompute as ffn8 at 2x the residual memory — OOM-bound at
        # bench scale (PROFILE_r03/r04), kept for smaller models.
        if ffn_fn is _dense_ffn:
            ffn_fn = _dense_ffn_save
            body = partial(
                _block, cos=cos, sin=sin, cfg=cfg, attn_fn=attn_fn,
                ffn_fn=ffn_fn,
            )
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse", "flash_qkv",
                "ffn_gate", "ffn_up",
            ),
        )
    elif cfg.remat == "flash_qkv_ffn8":
        # "flash_qkv" plus int8-saved FFN activations: the replay skips
        # the two FFN up-projection matmuls too, from residuals stored
        # at half the bf16 footprint (gate over loss parity — see
        # PROFILE_r04).
        if ffn_fn is _dense_ffn:
            ffn_fn = _dense_ffn_q8
            body = partial(
                _block, cos=cos, sin=sin, cfg=cfg, attn_fn=attn_fn,
                ffn_fn=ffn_fn,
            )
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse", "flash_qkv",
                "ffn_gate", "ffn_gate_scale", "ffn_up", "ffn_up_scale",
            ),
        )
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )

    def scan_fn(carry, layer_params):
        x, aux_sum = carry
        x, aux = body(x, layer_params)
        return (x, aux_sum + aux), None

    (x, aux_total), _ = jax.lax.scan(
        scan_fn, (x, jnp.float32(0.0)), params["blocks"]
    )

    x = rms_norm(x, params["final_norm"])
    if return_hidden:
        return x, aux_total
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, aux_total


def forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    attn_fn: AttnFn | None = None,
) -> jnp.ndarray:
    """tokens [B, S] int32 → logits [B, S, V] fp32."""
    logits, _ = forward_with_aux(params, tokens, cfg, attn_fn=attn_fn)
    return logits
