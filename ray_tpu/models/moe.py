"""Mixture-of-Experts decoder with native expert parallelism.

GShard/Switch-style MoE done the TPU way: routing is a static-shape
einsum pipeline (top-k gates → capacity-bounded one-hot dispatch tensor →
dispatch einsum → expert FFNs → combine einsum). Tokens are routed in
fixed-size GROUPS (GShard §3.2) so the dispatch tensors stay
O(groups · g²) with a bounded group size instead of O((B·S)²). Experts
carry the "expert" logical axis, sharded over the mesh's ep axis — XLA
inserts the token all-to-alls during SPMD partitioning; there is no
manual routing code on the host.

The attention sublayer, scan scaffolding, and non-expert parameters are
the flagship Llama's (ray_tpu.models.llama — this module only swaps the
FFN hook). The reference ships no MoE/expert parallelism at all
(SURVEY.md §2.3: TP/PP/EP "not implemented in Ray itself"); this makes
EP a first-class strategy next to DP/FSDP/TP/SP.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (
    LlamaConfig,
    Params,
    forward_with_aux,
    init_params,
    param_logical_axes,
)
from ray_tpu.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    # capacity per expert per group = capacity_factor * g * top_k / num_experts
    capacity_factor: float = 1.25
    # routing group size (tokens); bounds the dispatch tensor at
    # O(g * capacity) per group regardless of batch*seq.
    group_size: int = 1024
    # weight of the load-balancing auxiliary loss (Switch §2.2)
    aux_loss_weight: float = 0.01


MOE_PRESETS: dict[str, MoEConfig] = {
    "moe_tiny": MoEConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=256, dtype=jnp.float32, remat="none",
        num_experts=4, top_k=2, group_size=64,
    ),
    # Single-chip scale (fp32 master params + adam fit a v5e's HBM).
    "moe_bench": MoEConfig(
        vocab_size=32768, d_model=1024, n_layers=6, n_heads=16,
        n_kv_heads=8, d_ff=2048, max_seq=2048, num_experts=4, top_k=2,
    ),
    # Pod scale: experts sharded over the ep axis (won't fit one chip).
    "moe_8x430m": MoEConfig(
        vocab_size=32768, d_model=1024, n_layers=12, n_heads=16,
        n_kv_heads=8, d_ff=4096, max_seq=2048, num_experts=8, top_k=2,
    ),
}


def moe_param_logical_axes(cfg: MoEConfig) -> Params:
    axes = param_logical_axes(cfg)
    axes["blocks"].update(
        router=("layers", "embed", "expert"),
        w_gate=("layers", "expert", "embed", "mlp"),
        w_up=("layers", "expert", "embed", "mlp"),
        w_down=("layers", "expert", "mlp", "embed"),
    )
    return axes


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> Params:
    d, f, e = cfg.d_model, cfg.d_ff, cfg.num_experts
    L = cfg.n_layers
    base_key, *keys = jax.random.split(key, 5)

    def w(k, shape, fan_in):
        return (
            jax.random.truncated_normal(k, -2.0, 2.0, shape, jnp.float32)
            * fan_in**-0.5
        )

    params = init_params(base_key, cfg)
    params["blocks"].update(
        router=w(keys[0], (L, d, e), d),
        w_gate=w(keys[1], (L, e, d, f), d),
        w_up=w(keys[2], (L, e, d, f), d),
        w_down=w(keys[3], (L, e, f, d), f),
    )
    return params


def moe_ffn(x: jnp.ndarray, p: Params, cfg: MoEConfig):
    """FFN hook for llama._block: x [B, S, d] → (out, aux_loss).

    Static-shape grouped dispatch: every expert gets exactly `capacity`
    slots per group; overflow tokens are dropped (their residual passes
    through) — the standard TPU MoE trade (GShard §3.2).
    """
    b, s, d = x.shape
    e, k = cfg.num_experts, cfg.top_k
    n = b * s
    g = min(cfg.group_size, n)
    if n % g:
        g = n  # fall back to one group rather than failing odd shapes
    G = n // g
    capacity = max(1, int(cfg.capacity_factor * g * k / e))
    dt = cfg.dtype

    tokens = x.reshape(G, g, d)
    logits = (
        jnp.einsum("Ggd,de->Gge", tokens, p["router"].astype(dt))
    ).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [G, g, e]

    # Top-k gates, renormalized over the selected experts.
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [G, g, k]
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

    # Slot of each (token, choice) within its expert's per-group capacity.
    sel = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)  # [G, g, k, e]
    flat_sel = sel.reshape(G, g * k, e)
    pos_in_expert = jnp.cumsum(flat_sel, axis=1) - flat_sel
    slot = (pos_in_expert * flat_sel).sum(-1).reshape(G, g, k)
    keep = (slot < capacity).astype(jnp.float32)

    slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)  # [G,g,k,c]
    masked = slot_oh * keep[..., None]
    dispatch = jnp.einsum("Ggke,Ggkc->Ggec", sel.astype(jnp.float32), masked)
    combine = jnp.einsum(
        "Ggk,Ggke,Ggkc->Ggec", gate_vals, sel.astype(jnp.float32), masked
    )

    # [e, G, capacity, d] expert inputs — sharding e over ep makes XLA
    # emit the all-to-all here.
    expert_in = jnp.einsum(
        "Ggec,Ggd->eGcd", dispatch, tokens.astype(jnp.float32)
    )
    expert_in = constrain(
        expert_in.astype(dt), "expert", None, None, "act_embed"
    )
    gate = jax.nn.silu(
        jnp.einsum("eGcd,edf->eGcf", expert_in, p["w_gate"].astype(dt))
    )
    up = jnp.einsum("eGcd,edf->eGcf", expert_in, p["w_up"].astype(dt))
    expert_out = jnp.einsum(
        "eGcf,efd->eGcd", gate * up, p["w_down"].astype(dt)
    )
    expert_out = constrain(expert_out, "expert", None, None, "act_embed")

    out = jnp.einsum(
        "Ggec,eGcd->Ggd", combine, expert_out.astype(jnp.float32)
    ).astype(dt)

    # Load-balance aux loss: e * sum_e (fraction routed) * (mean prob),
    # averaged over groups (Switch §2.2).
    me = probs.mean(1)  # [G, e]
    ce = sel.astype(jnp.float32).sum(2).mean(1)  # [G, e]
    aux = e * (me * ce).sum(-1).mean() * cfg.aux_loss_weight
    return out.reshape(b, s, d), aux


def moe_forward(
    params: Params,
    tokens: jnp.ndarray,
    cfg: MoEConfig,
    attn_fn=None,
    return_hidden: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [B, S] → (logits [B, S, V] fp32 — or final hidden states
    with ``return_hidden`` — and the mean aux loss)."""
    out, aux_total = forward_with_aux(
        params, tokens, cfg, attn_fn=attn_fn, ffn_fn=moe_ffn,
        return_hidden=return_hidden,
    )
    return out, aux_total / cfg.n_layers
