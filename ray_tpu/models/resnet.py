"""ResNet for image classification (BASELINE config 2: JaxTrainer DP
ResNet/CIFAR on v5e-8; reference counterpart: the torch ResNet examples
under python/ray/train/examples/).

TPU-first choices: convs in bf16 feed the MXU via
``lax.conv_general_dilated`` in NHWC (the TPU-native layout); GroupNorm
instead of BatchNorm so the model is a pure function of (params, batch)
— no mutable running stats to thread through pjit, and normalization is
independent of the per-chip batch split under data parallelism (BN
would silently change semantics with the dp shard size)."""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

Params = Any


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (2, 2, 2, 2)  # ResNet-18 layout
    widths: tuple = (64, 128, 256, 512)
    num_classes: int = 10
    groups: int = 32  # GroupNorm groups (clamped per width)
    dtype: Any = jnp.bfloat16
    stem_kernel: int = 3  # 3 for CIFAR-sized inputs, 7 for ImageNet
    stem_stride: int = 1  # 2 + stem_pool for the ImageNet 4x stem
    stem_pool: bool = False  # stride-2 3x3 maxpool after the stem
    bottleneck: bool = False  # True → 3-layer blocks (ResNet-50 style)

    @property
    def stem_width(self) -> int:
        return 64 if self.bottleneck else self.widths[0]

    def num_params(self) -> int:
        shapes = jax.eval_shape(
            lambda k: init_params(k, self), jax.random.key(0)
        )
        # math.prod over .shape: jnp.size on a ShapeDtypeStruct is
        # deprecated (DeprecationWarning per leaf, removal planned).
        import math

        return sum(
            math.prod(p.shape) for p in jax.tree.leaves(shapes)
        )


PRESETS = {
    "resnet18": ResNetConfig(),
    "resnet50": ResNetConfig(
        stage_sizes=(3, 4, 6, 3),
        widths=(256, 512, 1024, 2048),
        bottleneck=True,
        stem_kernel=7,
        stem_stride=2,  # + maxpool = the canonical 4x ImageNet stem
        stem_pool=True,
        num_classes=1000,
    ),
    # Tiny config for unit tests / dry runs.
    "tiny": ResNetConfig(
        stage_sizes=(1, 1), widths=(8, 16), groups=4, num_classes=10
    ),
}


def _conv_init(key, kh, kw, cin, cout):
    scale = jnp.sqrt(2.0 / (kh * kw * cin))
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * scale


def _conv(p, x, stride=1, dtype=jnp.bfloat16):
    return jax.lax.conv_general_dilated(
        x.astype(dtype),
        p.astype(dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _gn_init(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _group_norm(p, x, groups):
    b, h, w, c = x.shape
    g = min(groups, c)
    while c % g:
        g -= 1
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    xf = xf.reshape(b, h, w, c)
    return (xf * p["scale"] + p["bias"]).astype(x.dtype)


def _block_init(key, cin, cout, cfg: ResNetConfig):
    keys = jax.random.split(key, 4)
    if cfg.bottleneck:
        mid = cout // 4
        p = {
            "conv1": _conv_init(keys[0], 1, 1, cin, mid),
            "gn1": _gn_init(mid),
            "conv2": _conv_init(keys[1], 3, 3, mid, mid),
            "gn2": _gn_init(mid),
            "conv3": _conv_init(keys[2], 1, 1, mid, cout),
            "gn3": _gn_init(cout),
        }
    else:
        p = {
            "conv1": _conv_init(keys[0], 3, 3, cin, cout),
            "gn1": _gn_init(cout),
            "conv2": _conv_init(keys[1], 3, 3, cout, cout),
            "gn2": _gn_init(cout),
        }
    if cin != cout:
        p["proj"] = _conv_init(keys[3], 1, 1, cin, cout)
        p["gn_proj"] = _gn_init(cout)
    return p


def _block_apply(p, x, stride, cfg: ResNetConfig):
    dtype = cfg.dtype
    residual = x
    if cfg.bottleneck:
        y = _conv(p["conv1"], x, 1, dtype)
        y = jax.nn.relu(_group_norm(p["gn1"], y, cfg.groups))
        y = _conv(p["conv2"], y, stride, dtype)
        y = jax.nn.relu(_group_norm(p["gn2"], y, cfg.groups))
        y = _conv(p["conv3"], y, 1, dtype)
        y = _group_norm(p["gn3"], y, cfg.groups)
    else:
        y = _conv(p["conv1"], x, stride, dtype)
        y = jax.nn.relu(_group_norm(p["gn1"], y, cfg.groups))
        y = _conv(p["conv2"], y, 1, dtype)
        y = _group_norm(p["gn2"], y, cfg.groups)
    if "proj" in p or stride != 1:
        if "proj" in p:
            residual = _conv(p["proj"], residual, stride, dtype)
            residual = _group_norm(p["gn_proj"], residual, cfg.groups)
        else:  # same width, spatial downsample only
            residual = residual[:, ::stride, ::stride, :]
    return jax.nn.relu(y + residual.astype(y.dtype))


def init_params(key, cfg: ResNetConfig) -> Params:
    keys = jax.random.split(key, 2 + sum(cfg.stage_sizes))
    params: dict = {
        "stem": _conv_init(
            keys[0], cfg.stem_kernel, cfg.stem_kernel, 3, cfg.stem_width
        ),
        "gn_stem": _gn_init(cfg.stem_width),
        "blocks": [],
    }
    cin = cfg.stem_width
    ki = 1
    for si, (n, width) in enumerate(zip(cfg.stage_sizes, cfg.widths)):
        for bi in range(n):
            params["blocks"].append(
                _block_init(keys[ki], cin, width, cfg)
            )
            cin = width
            ki += 1
    params["head"] = {
        "w": jax.random.normal(
            keys[-1], (cin, cfg.num_classes), jnp.float32
        ) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params


def forward(params: Params, images: jnp.ndarray, cfg: ResNetConfig):
    """images [B, H, W, 3] float → logits [B, num_classes] (f32)."""
    x = _conv(params["stem"], images, cfg.stem_stride, cfg.dtype)
    x = jax.nn.relu(_group_norm(params["gn_stem"], x, cfg.groups))
    if cfg.stem_pool:
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, 3, 3, 1),
            window_strides=(1, 2, 2, 1),
            padding="SAME",
        )
    bi = 0
    for si, n in enumerate(cfg.stage_sizes):
        for block_i in range(n):
            stride = 2 if (si > 0 and block_i == 0) else 1
            x = _block_apply(params["blocks"][bi], x, stride, cfg)
            bi += 1
    x = x.astype(jnp.float32).mean(axis=(1, 2))  # global average pool
    return x @ params["head"]["w"] + params["head"]["b"]


def loss_fn(params, batch, cfg: ResNetConfig):
    """Softmax cross entropy; batch = {"images": [B,H,W,3],
    "labels": [B]}."""
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits)
    ll = jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)
    acc = (logits.argmax(-1) == batch["labels"]).mean()
    return -ll.mean(), {"accuracy": acc}
