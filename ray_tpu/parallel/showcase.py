"""Composed-parallelism reference program: pp x ep x fsdp in ONE mesh.

A minimal but complete composition of the three mechanisms a pod run
stacks (SURVEY.md §3.4): GPipe pipeline stages (pp) whose bodies are
expert-parallel MoE blocks (ep, psum-combined dispatch) with a
ZeRO-3-sharded dense weight (fsdp, all_gathered at use), data sharded
over fsdp. Used by the driver's multichip dry run (__graft_entry__)
both directly and through JaxTrainer.fit(), so the exact program a
pod would compile is exercised through the real Train control plane.
"""

from __future__ import annotations

N_EXPERTS = 4
D = 8
PP = 2


def make_composed_params(key):
    import jax

    k = jax.random.split(key, 2)
    return {
        # [pp, E, d, d]: stage dim over pp, experts over ep.
        "experts": jax.random.normal(k[0], (PP, N_EXPERTS, D, D)) * 0.3,
        # [pp, d, d]: ZeRO-3 over fsdp (gathered inside the stage).
        "dense": jax.random.normal(k[1], (PP, D, D)) * 0.3,
    }


def composed_param_specs():
    from jax.sharding import PartitionSpec as P

    return {
        "experts": P("pp", "ep"),
        "dense": P("pp", None, "fsdp"),
    }


def _stage_fn(p, x):  # x: [mb, d]
    import jax
    import jax.numpy as jnp

    # ZeRO-3: re-assemble the dense weight from its fsdp shards
    # (sharded on the last dim per P("pp", None, "fsdp")).
    w = jax.lax.all_gather(p["dense"], "fsdp", axis=1, tiled=True)
    x = x + jnp.tanh(x @ w)
    # MoE dispatch: token i -> expert (i mod E); each device runs its
    # LOCAL experts, the combine is a psum over ep.
    local = p["experts"]  # [E/ep, d, d]
    e_local = local.shape[0]
    ep_idx = jax.lax.axis_index("ep")
    outs = jnp.einsum("md,edh->emh", x, local)  # [E/ep, mb, d]
    assigned = (jnp.abs(x[:, 0]) * 100).astype(jnp.int32) % N_EXPERTS
    local_ids = ep_idx * e_local + jnp.arange(e_local)
    mask = assigned[None, :] == local_ids[:, None]  # [E/ep, mb]
    y = jnp.sum(outs * mask[..., None], axis=0)
    y = jax.lax.psum(y, "ep")
    return x + jnp.tanh(y)


def composed_value_and_grad(params, mesh):
    """One fwd+bwd of the composed program on `mesh` (axes pp/ep/fsdp).
    Returns (loss, grads); batch is synthesized to fill the fsdp axis."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.parallel.pipeline import pipeline_loss_fn

    fsdp = dict(zip(mesh.axis_names, mesh.devices.shape))["fsdp"]

    def loss_head(y, batch):
        return jnp.mean(y**2)

    batch = 2 * fsdp * 2  # microbatches x fsdp shards x mb
    return jax.value_and_grad(
        lambda p: pipeline_loss_fn(
            p,
            {"inputs": jnp.ones((batch, D))},
            _stage_fn,
            loss_head,
            mesh=mesh,
            num_microbatches=2,
            param_specs=composed_param_specs(),
        )
    )(params)


def composed_trainer_loop(config):
    """train_loop_per_worker for JaxTrainer: builds the composed
    {pp:2, ep:2, fsdp:N} mesh and runs real optimizer steps over the
    composed program, reporting metrics and a checkpoint through the
    Train session (exercises worker group + checkpoint plumbing). Steps
    are wrapped in train.step_span with compute/collective phases and a
    flight-recorder-visible cross-worker metric sync, so the head
    goodput ledger gets per-phase time AND comm-exposure attribution
    (comm_exposed_s vs comm_overlapped_s) from this loop — the dryrun
    asserts it."""
    import os
    import tempfile

    import jax
    import numpy as np

    import ray_tpu.train as train
    from ray_tpu import collective as col
    from ray_tpu.parallel import make_mesh

    ctx = train.get_context()
    mesh = make_mesh({"pp": 2, "ep": 2, "fsdp": int(config["fsdp"])})
    params = make_composed_params(jax.random.key(7))
    # Attempt-scoped group name: an elastic retry must not rendezvous
    # with a dead attempt's KV keys.
    gname = f"composed_sync{ctx.attempt}"
    col.init_collective_group(
        ctx.get_world_size(), ctx.get_world_rank(), backend="cpu",
        group_name=gname,
    )
    loss = None
    # Bucketed overlap gradient sync (ScalingConfig.grad_overlap): the
    # step loop issues per-bucket async allreduces for the REAL grads
    # eagerly inside the compute phase and joins the handles just
    # before the optimizer update — the canonical overlapped-step
    # shape the dryrun drives end to end through JaxTrainer.fit().
    overlap = bool(train.grad_sync_opts().get("overlap"))
    n_buckets = 0
    try:
        for step in range(int(config.get("steps", 2))):
            with train.step_span() as sp:
                pending = None
                with sp.phase("compute"):
                    loss, grads = composed_value_and_grad(params, mesh)
                    if overlap:
                        bucketer = train.grad_bucketer(group_name=gname)
                        pending = bucketer.sync_async(grads)
                        # In-flight buckets overlap this remaining
                        # compute (grad-norm probe): reduce on device,
                        # pay ONE host transfer for the scalar.
                        sq = sum(
                            jax.numpy.sum(g * g)
                            for g in jax.tree.leaves(grads)
                        )
                        # tpulint: allow(TPU601 reason=deliberate - this single scalar sync IS the remaining in-phase work the in-flight buckets overlap with; the dryrun asserts comm_overlapped_s>0 against exactly this probe)
                        gnorm = float(np.sqrt(float(sq)))
                with sp.phase("collective"):
                    # Cross-worker loss mean through the recorded
                    # collective path (the compiled program's psums are
                    # invisible to the flight recorder; this op is what
                    # the comm-exposure ledger attributes).
                    mean_loss = col.allreduce(
                        np.asarray([float(loss)], np.float32),
                        group_name=gname,
                    )[0] / max(1, ctx.get_world_size())
                    if pending is not None:
                        # Join tail: only what did not finish during
                        # compute shows up as exposed comm.
                        synced = bucketer.unflatten(grads, pending.wait())
                        world = max(1, ctx.get_world_size())
                        grads = jax.tree.map(
                            lambda g: np.asarray(g) / world, synced
                        )
                        n_buckets = len(pending.buckets)
                with sp.phase("compute"):
                    params = jax.tree.map(
                        lambda p, g: p - 0.1 * g, params, grads
                    )
            ckpt = None
            if ctx.get_world_rank() == 0:
                ckpt = tempfile.mkdtemp(prefix="composed_ck_")
                np.savez(
                    os.path.join(ckpt, "params.npz"),
                    **{k: np.asarray(v) for k, v in params.items()},
                )
            metrics = {
                "loss": float(mean_loss), "step": step,
                "mesh": {"pp": 2, "ep": 2, "fsdp": int(config["fsdp"])},
            }
            if overlap:
                metrics["grad_buckets"] = n_buckets
                metrics["grad_norm"] = gnorm
            train.report(metrics, checkpoint=ckpt)
    finally:
        col.destroy_collective_group(gname)
