"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis.

TPU-native PP is one SPMD program, not a runtime of stage processes: each
device along `pp` holds ONE stage's parameters; a `lax.scan` runs the
circulating schedule (stage s works on microbatch t-s at step t) and
`lax.ppermute` hands activations to the next stage over ICI. Because the
whole schedule lives inside jit, `jax.grad` through it yields the 1F1B-ish
backward for free — XLA pipelines the reverse ppermutes the same way.

The reference has no native PP (SURVEY.md §2.3: delegated to vLLM and to
compiled-graph NCCL P2P channels); this module is the substrate that
fills it, alongside dag/ for cross-process pipelines.

Bubble fraction is the GPipe (P-1)/(M+P-1); pick num_microbatches >= 4*P
to amortize.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

StageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def pipeline_apply(
    stage_params: Any,
    x: jnp.ndarray,  # [batch, ...] global inputs
    stage_fn: StageFn,  # (one stage's params, microbatch) -> microbatch
    *,
    mesh,
    num_microbatches: int,
    axis: str = "pp",
    param_specs: Any = None,
) -> jnp.ndarray:
    """Run x through P chained stages, microbatched and pipelined.

    ``stage_params`` leaves have a leading stage dim P (sharded over
    ``axis``); every stage must map [mb, ...] → [mb, ...] of the same
    shape (the circulating buffer is homogeneous). Returns the last
    stage's outputs for the full batch, replicated over ``axis``.

    ``param_specs`` (optional tree of PartitionSpecs, leading dim =
    ``axis``) shards stage-param dims over FURTHER mesh axes — e.g.
    ``P("pp", "ep")`` for expert-stacked MoE weights or
    ``P("pp", None, "fsdp")`` for ZeRO-3 stage weights — and
    ``stage_fn`` then uses those axes collectively (psum over "ep",
    all_gather over "fsdp"): pipeline, expert, and data/ZeRO
    parallelism compose inside ONE shard_map program.
    """
    n_stages = mesh.shape[axis]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stage dim {leaf.shape[0]} != mesh {axis}={n_stages}; a "
                "mismatch would silently drop stages"
            )
    if param_specs is not None:
        for spec in jax.tree.leaves(
            param_specs, is_leaf=lambda s: isinstance(s, P)
        ):
            first = spec[0] if len(spec) else None
            names = first if isinstance(first, tuple) else (first,)
            if axis not in names:
                raise ValueError(
                    f"param_specs leaf {spec} must shard its LEADING "
                    f"dim over {axis!r}; otherwise every device would "
                    "silently run stage 0's weights"
                )
    # Batch shards over the data axes (pipeline composes with DP); each
    # dp shard runs its own GPipe schedule on its slice.
    dp_axes = tuple(
        a for a in ("dp", "fsdp") if a in mesh.shape and mesh.shape[a] > 1
    )
    dp_total = 1
    for a in dp_axes:
        dp_total *= mesh.shape[a]
    batch = x.shape[0]
    if batch % (num_microbatches * dp_total):
        raise ValueError(
            f"batch {batch} not divisible by microbatches "
            f"{num_microbatches} x data shards {dp_total}"
        )
    mb = batch // dp_total // num_microbatches

    def per_device(params_local, x_full):
        # params_local leaves: [1, ...] (this device's stage); x_full is
        # this data shard's slice of the batch (replicated over pp).
        params_one = jax.tree.map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index(axis)
        micro = x_full.reshape(num_microbatches, mb, *x_full.shape[1:])

        num_steps = num_microbatches + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            recv, outputs = carry
            # Stage 0 ingests microbatch t (clamped; masked later).
            feed = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(t, 0, num_microbatches - 1), 0,
                keepdims=False,
            )
            x_in = jnp.where(stage == 0, feed, recv)
            y = stage_fn(params_one, x_in)
            # The last stage completes microbatch t - (P-1) at step t.
            out_idx = t - (n_stages - 1)
            valid = jnp.logical_and(
                stage == n_stages - 1, out_idx >= 0
            )
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(
                    valid,
                    y,
                    jax.lax.dynamic_index_in_dim(
                        outputs, jnp.clip(out_idx, 0, num_microbatches - 1),
                        0, keepdims=False,
                    ),
                ),
                jnp.clip(out_idx, 0, num_microbatches - 1),
                0,
            )
            # Rotate activations one stage forward over ICI.
            recv_next = jax.lax.ppermute(y, axis, perm)
            return (recv_next, outputs), None

        outputs0 = jnp.zeros_like(micro)
        recv0 = jnp.zeros((mb, *x_full.shape[1:]), x_full.dtype)
        (recv, outputs), _ = jax.lax.scan(
            step, (recv0, outputs0), jnp.arange(num_steps)
        )
        # Only the last stage holds real outputs; replicate via psum.
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, 0.0), axis
        )
        return outputs.reshape(-1, *x_full.shape[1:])

    spec_params = (
        param_specs
        if param_specs is not None
        else jax.tree.map(lambda _: P(axis), stage_params)
    )
    batch_spec = P(dp_axes if dp_axes else None)
    return shard_map(
        per_device,
        mesh=mesh,
        in_specs=(spec_params, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(stage_params, x)


def pipeline_loss_fn(
    stage_params: Any,
    batch: dict,
    stage_fn: StageFn,
    loss_head: Callable[[jnp.ndarray, dict], jnp.ndarray],
    *,
    mesh,
    num_microbatches: int,
    param_specs: Any = None,
) -> jnp.ndarray:
    """Differentiable pipelined loss: forward through the stages, then a
    replicated loss head (logits → scalar). Use under jax.grad/jit."""
    y = pipeline_apply(
        stage_params,
        batch["inputs"],
        stage_fn,
        mesh=mesh,
        num_microbatches=num_microbatches,
        param_specs=param_specs,
    )
    return loss_head(y, batch)
