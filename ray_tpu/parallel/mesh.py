"""Device-mesh construction for TPU pods.

The mesh is the TPU-native unit of parallel execution: instead of the
reference's per-rank process groups (reference:
python/ray/util/collective/collective.py:171 `init_collective_group` with
explicit world_size/rank), a JAX `Mesh` names the parallelism axes and XLA
compiles collectives over ICI/DCN into the program.

Canonical axis order (outer → inner, DCN-ish → ICI-ish):

    dp    pure data parallelism (gradient psum, no param sharding)
    fsdp  data parallelism with parameters/optimizer sharded (ZeRO-3 style)
    pp    pipeline parallelism (layer stages; ray_tpu.parallel.pipeline
          runs the GPipe microbatch schedule over this axis)
    ep    expert parallelism (MoE experts spread over chips)
    tp    tensor parallelism (heads / mlp / vocab sharded)
    sp    sequence/context parallelism (ring attention, Ulysses)
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

# Canonical mesh axes, outer-to-inner. Axes of size 1 are always present so
# sharding rules never need to special-case a missing axis.
MESH_AXES = ("dp", "fsdp", "pp", "ep", "tp", "sp")


def default_axis_sizes(n_devices: int) -> dict[str, int]:
    """Pick a reasonable axis factorization for ``n_devices``.

    Heuristic for tests/dry-runs: give tp, sp, then fsdp a factor of 2
    when it divides, put the remainder in dp — exercising every axis kind
    that fits. Real jobs should pass explicit sizes.
    """
    sizes = {a: 1 for a in MESH_AXES}
    rem = int(n_devices)
    for axis in ("tp", "sp", "fsdp"):
        if rem % 2 == 0 and rem > 1:
            sizes[axis] = 2
            rem //= 2
    sizes["dp"] = rem
    return sizes


def _resolve_sizes(
    axis_sizes: Mapping[str, int], n_devices: int
) -> dict[str, int]:
    sizes = {a: int(axis_sizes.get(a, 1)) for a in MESH_AXES}
    unknown = set(axis_sizes) - set(MESH_AXES)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; valid axes: {MESH_AXES}"
        )
    wildcards = [a for a, s in sizes.items() if s == -1]
    if len(wildcards) > 1:
        raise ValueError("at most one axis size may be -1")
    fixed = 1
    for a, s in sizes.items():
        if s != -1:
            if s < 1:
                raise ValueError(f"axis {a!r} has invalid size {s}")
            fixed *= s
    if wildcards:
        if n_devices % fixed != 0:
            raise ValueError(
                f"cannot fill axis {wildcards[0]!r}: {n_devices} devices not "
                f"divisible by {fixed}"
            )
        sizes[wildcards[0]] = n_devices // fixed
        fixed = n_devices
    if fixed != n_devices:
        raise ValueError(
            f"mesh axis sizes {sizes} multiply to {fixed}, but there are "
            f"{n_devices} devices"
        )
    return sizes


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a `jax.sharding.Mesh` over ``devices`` with canonical axes.

    ``axis_sizes`` maps axis name → size; missing axes get size 1; one axis
    may be -1 to absorb the remaining device count. With no ``axis_sizes``
    at all, all devices land on ``dp``.

    On real TPU slices, `jax.devices()` ordering already reflects the
    physical torus, so reshaping in canonical order keeps `tp`/`sp` (the
    innermost axes, where collectives are latency-sensitive) on nearest-
    neighbor ICI links.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = {"dp": n}
    sizes = _resolve_sizes(axis_sizes, n)
    dev_array = np.asarray(devices, dtype=object).reshape(
        [sizes[a] for a in MESH_AXES]
    )
    return Mesh(dev_array, MESH_AXES)


def make_multislice_mesh(
    ici_axis_sizes: Mapping[str, int],
    dcn_axis_sizes: Mapping[str, int],
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Hybrid mesh for MULTISLICE pods: ``dcn_axis_sizes`` axes span
    slices over the data-center network (gradient-sized, latency-tolerant
    collectives — normally ``dp``/``fsdp``); ``ici_axis_sizes`` axes
    shard within a slice on the torus (``tp``/``sp``/``ep``, where
    collectives are latency-critical).

    Uses jax's hybrid mesh builder so same-slice devices stay contiguous
    on the inner axes (reference scaling recipe: DCN outermost, ICI
    innermost — the multislice layout of the scaling book; reference's
    NCCL/MPI analogue is the multi-node process-group split in
    torch/config.py:73). Falls back to a flat mesh when devices carry no
    slice topology (CPU tests, single slice)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    # Validate both dicts up front (the multislice path would otherwise
    # silently drop typo'd axes that the flat path rejects), and refuse
    # wildcards — a -1 in either factor is ambiguous across the split.
    for name, sizes in (("ici", ici_axis_sizes), ("dcn", dcn_axis_sizes)):
        unknown = set(sizes) - set(MESH_AXES)
        if unknown:
            raise ValueError(
                f"unknown {name} mesh axes {sorted(unknown)}; valid: "
                f"{MESH_AXES}"
            )
        if any(int(v) < 1 for v in sizes.values()):
            raise ValueError(
                f"{name}_axis_sizes must be explicit positive sizes "
                f"(no -1 wildcards): {dict(sizes)}"
            )
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    combined = {
        a: int(ici_axis_sizes.get(a, 1)) * int(dcn_axis_sizes.get(a, 1))
        for a in set(ici_axis_sizes) | set(dcn_axis_sizes)
    }
    if n_slices <= 1:
        # Single slice (or no slice metadata): DCN factors fold into the
        # flat mesh — shardings and programs stay identical, only the
        # physical layout differs.
        return make_mesh(combined, devices=devices)
    sizes_ici = _resolve_sizes(
        {a: int(ici_axis_sizes.get(a, 1)) for a in MESH_AXES},
        n // int(np.prod([dcn_axis_sizes.get(a, 1) for a in MESH_AXES])),
    )
    sizes_dcn = {a: int(dcn_axis_sizes.get(a, 1)) for a in MESH_AXES}
    from jax.experimental import mesh_utils

    dev_array = mesh_utils.create_hybrid_device_mesh(
        [sizes_ici[a] for a in MESH_AXES],
        [sizes_dcn[a] for a in MESH_AXES],
        devices=devices,
        allow_split_physical_axes=True,
    )
    # Unwrap fake-slice shims (fake_slice_devices below): the hybrid
    # ARRANGEMENT ran on the shims' slice_index; the Mesh must hold the
    # real runtime devices.
    unwrap = np.vectorize(
        lambda d: getattr(d, "_raytpu_device", d), otypes=[object]
    )
    return Mesh(unwrap(dev_array), MESH_AXES)


class _FakeSliceDevice:
    """Attribute-forwarding shim giving a device a fake slice_index —
    lets single-slice rigs (virtual CPU meshes, one real chip) drive
    make_multislice_mesh's REAL hybrid arrangement path in tests and
    dryruns. make_multislice_mesh unwraps these before building the
    Mesh."""

    def __init__(self, device, slice_index: int):
        self._raytpu_device = device
        self.slice_index = slice_index

    def __getattr__(self, name):
        return getattr(self._raytpu_device, name)

    def __repr__(self):
        return f"FakeSlice({self.slice_index}, {self._raytpu_device!r})"


def fake_slice_devices(
    n_slices: int, devices: Sequence[jax.Device] | None = None
) -> list:
    """Partition ``devices`` into ``n_slices`` contiguous fake slices
    (test/dryrun shim; see _FakeSliceDevice)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if len(devices) % n_slices:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_slices} slices"
        )
    per = len(devices) // n_slices
    return [
        _FakeSliceDevice(d, i // per) for i, d in enumerate(devices)
    ]
