"""Ulysses-style sequence parallelism: all-to-all head↔sequence re-shard.

The second SP strategy the reference lacks (SURVEY.md section 5). Where
ring attention rotates KV blocks, Ulysses transposes the sharding: each sp
shard holds all positions for a subset of heads during attention, so the
attention itself is entirely local — two all-to-alls (over ICI) bracket
it. Best when n_heads % sp == 0 and the sequence is long relative to the
ring's per-hop latency.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

from ray_tpu.ops.attention import causal_attention


def _a2a(x, axis_name, split_axis, concat_axis):
    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )


def ulysses_attention_kernel(
    q, k, v, *, axis_name: str, inner: Callable = causal_attention
):
    """Per-shard body under shard_map; q/k/v: [B, S_local, H, D].

    all_to_all: [B, S/n, H, D] → [B, S, H/n, D]; run full-sequence
    attention on the local head group; transpose back.
    """
    n = jax.lax.psum(1, axis_name)
    if q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads ({q.shape[2]}) divisible by sp ({n})"
        )
    qh = _a2a(q, axis_name, split_axis=2, concat_axis=1)
    kh = _a2a(k, axis_name, split_axis=2, concat_axis=1)
    vh = _a2a(v, axis_name, split_axis=2, concat_axis=1)
    oh = inner(qh, kh, vh)
    return _a2a(oh, axis_name, split_axis=1, concat_axis=2)


def make_ulysses_attention(mesh, batch_axes=("dp", "fsdp"), seq_axis="sp",
                           head_axis="tp"):
    spec = P(batch_axes, seq_axis, head_axis, None)
    kernel = partial(ulysses_attention_kernel, axis_name=seq_axis)
    return shard_map(
        kernel, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
