"""Logical-axis sharding rules (t5x/maxtext-style) for the canonical mesh.

Every tensor in a model carries a tuple of *logical* axis names; rules map
each logical axis to zero or more mesh axes. This is the TPU-native
equivalent of the reference's per-strategy process-group plumbing: the
reference wires DDP/FSDP through torch process groups
(reference: python/ray/train/torch/config.py:73) and delegates TP/SP to
external engines (SURVEY.md section 2.3); here one rule table expresses
DP, FSDP(ZeRO-3), TP, SP and EP simultaneously and XLA inserts the
collectives (all-gather of fsdp-sharded params, psum of grads, all-to-all
for experts) during SPMD partitioning.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# (logical axis, mesh axis or tuple of mesh axes or None)
#
# Activation axes:
#   batch      → sharded over both data axes (dp outer, fsdp inner)
#   act_seq    → sequence parallelism
#   act_embed  → replicated (activations keep full model dim)
#   act_heads  → tensor parallelism over attention heads
#   act_mlp    → tensor parallelism over the ffn hidden dim
# Parameter axes:
#   embed      → fsdp-sharded (ZeRO-3: each data shard owns a param slice)
#   heads      → tp-sharded fused (n_heads * head_dim) dim
#   kv_heads   → tp-sharded fused kv dim
#   mlp        → tp-sharded ffn hidden dim
#   vocab      → tp-sharded vocabulary dim
#   layers     → stacked-layer leading dim (scan), never sharded
#   expert     → expert parallelism
DEFAULT_RULES: tuple[tuple[str, Any], ...] = (
    ("batch", ("dp", "fsdp")),
    ("act_seq", "sp"),
    ("act_embed", None),
    ("act_heads", "tp"),
    ("act_mlp", "tp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("layers", None),
    ("stage", "pp"),
    ("expert", "ep"),
    (None, None),
)


def logical_spec(
    logical_axes: Sequence[str | None],
    rules: Sequence[tuple[str | None, Any]] = DEFAULT_RULES,
) -> PartitionSpec:
    """Translate a tuple of logical axis names into a PartitionSpec."""
    table = dict(rules)
    parts = []
    for ax in logical_axes:
        if ax not in table:
            raise ValueError(f"no sharding rule for logical axis {ax!r}")
        parts.append(table[ax])
    return PartitionSpec(*parts)


def logical_sharding(
    mesh: Mesh,
    logical_axes: Sequence[str | None],
    rules: Sequence[tuple[str | None, Any]] = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def is_axes_leaf(x: Any) -> bool:
    """True for a tuple of logical axis names (not a NamedTuple container)."""
    return (
        isinstance(x, tuple)
        and not hasattr(x, "_fields")
        and all(e is None or isinstance(e, str) for e in x)
    )


def tree_shardings(
    mesh: Mesh,
    logical_tree: Any,
    rules: Sequence[tuple[str | None, Any]] = DEFAULT_RULES,
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings.

    ``logical_tree`` must be a pytree whose leaves are tuples of logical
    axis names (plain tuples of str/None are treated as leaves; NamedTuple
    containers like TrainState are traversed).
    """
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=is_axes_leaf,
    )


_ACTIVE = threading.local()


@contextlib.contextmanager
def use_mesh(
    mesh: Mesh, rules: Sequence[tuple[str | None, Any]] = DEFAULT_RULES
):
    """Make (mesh, rules) ambient for `constrain` during jit tracing.

    Model code calls `constrain(x, "batch", "act_seq", ...)` without
    threading a mesh through every function; outside a use_mesh scope the
    call is a no-op so the same model runs unsharded.
    """
    prev = getattr(_ACTIVE, "ctx", None)
    _ACTIVE.ctx = (mesh, tuple(rules))
    try:
        yield
    finally:
        _ACTIVE.ctx = prev


def constrain(x: jax.Array, *logical_axes: str | None) -> jax.Array:
    """with_sharding_constraint by logical axes, no-op without use_mesh."""
    ctx = getattr(_ACTIVE, "ctx", None)
    if ctx is None:
        return x
    mesh, rules = ctx
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules)
    )


def shard_pytree(
    tree: Any,
    mesh: Mesh,
    logical_tree: Any,
    rules: Sequence[tuple[str | None, Any]] = DEFAULT_RULES,
) -> Any:
    """Device-put a pytree of arrays according to its logical axes."""
    shardings = tree_shardings(mesh, logical_tree, rules)
    return jax.tree.map(jax.device_put, tree, shardings)
