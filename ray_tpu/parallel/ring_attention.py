"""Ring attention: causal attention with the sequence sharded over `sp`.

The reference has no sequence/context parallelism (SURVEY.md section 5:
"Long-context / sequence parallelism: Not present"); this module fills
that gap TPU-natively. Each sp shard holds one sequence block of Q/K/V.
K/V blocks rotate around the ring via `ppermute` (nearest-neighbor ICI
hops) while each shard accumulates its queries' attention over every
block with streaming flash-style (max, denom) statistics — memory stays
O(block²) and the rotation overlaps with compute (the python loop is
unrolled, letting XLA schedule the next permute during the current
block's matmuls; cf. PAPERS.md ring/overlap literature).

Differentiable (pure jnp + ppermute, which has a transpose rule), so it
drops into the training step as the model's attention function.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu._private.jax_compat import shard_map

from ray_tpu.ops.attention import _repeat_kv

_NEG_BIG = -1.0e30


def _block_stats(q, k, v, q_off, kv_off):
    """One Q-block × KV-block partial attention.

    Returns (o, m, l): unnormalized output [B,Sq,H,D] = exp(S - m) @ V,
    rowmax m and rowsum l, both [B,H,Sq], fp32. Fully-masked rows give
    m=_NEG_BIG, l=0, o=0 so they vanish in the streaming combine.
    """
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    logits = (
        jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    q_pos = jnp.arange(q.shape[1]) + q_off
    k_pos = jnp.arange(k.shape[1]) + kv_off
    mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
    logits = jnp.where(mask, logits, _NEG_BIG)
    m = jnp.max(logits, axis=-1)  # [B,H,Sq]
    p = jnp.exp(logits - m[..., None]) * mask  # masked rows → 0
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(
        jnp.float32
    )
    return o, m, l


def ring_attention_kernel(q, k, v, *, axis_name: str):
    """Per-shard body; call under shard_map with seq sharded on
    ``axis_name``. q/k/v: [B, S_local, H(or Hkv), D]."""
    n = jax.lax.psum(1, axis_name)
    r = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_off = r * s_local

    b, _, h, d = q.shape
    o = jnp.zeros((b, s_local, h, d), jnp.float32)
    m = jnp.full((b, h, s_local), _NEG_BIG, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)

    fwd = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        # This iteration's KV block came from rank (r - step) mod n.
        kv_rank = (r - step) % n
        kv_off = kv_rank * s_local
        o_b, m_b, l_b = _block_stats(q, k, v, q_off, kv_off)
        # Streaming (flash) combine in fp32.
        m_new = jnp.maximum(m, m_b)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_b - m_new)
        o = o * alpha.transpose(0, 2, 1)[..., None] + o_b * beta.transpose(
            0, 2, 1
        )[..., None]
        l = l * alpha + l_b * beta
        m = m_new
        if step != n - 1:
            k = jax.lax.ppermute(k, axis_name, perm=fwd)
            v = jax.lax.ppermute(v, axis_name, perm=fwd)

    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def make_ring_attention(mesh, batch_axes=("dp", "fsdp"), seq_axis="sp",
                        head_axis="tp"):
    """Build an attention fn (q,k,v → o, all [B,S,H,D] global) running the
    ring kernel under shard_map on ``mesh``. Drop-in for
    ray_tpu.models.llama.forward(attn_fn=...)."""
    spec = P(batch_axes, seq_axis, head_axis, None)
    kernel = partial(ring_attention_kernel, axis_name=seq_axis)
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
