"""Parallelism layer: device meshes, sharding rules, SP/PP/EP strategies.

TPU-native replacement for the reference's parallelism surface
(reference: python/ray/util/collective/collective.py, python/ray/dag/ for PP,
and the gap analysis in SURVEY.md section 2.3 — the reference delegates
TP/PP/EP/SP to external engines; here they are first-class jax shardings).
"""

from ray_tpu.parallel.mesh import (
    MESH_AXES,
    default_axis_sizes,
    make_mesh,
    make_multislice_mesh,
)
from ray_tpu.parallel.pipeline import pipeline_apply, pipeline_loss_fn
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_sharding,
    logical_spec,
    shard_pytree,
    tree_shardings,
)

__all__ = [
    "pipeline_apply",
    "pipeline_loss_fn",
    "MESH_AXES",
    "default_axis_sizes",
    "make_mesh",
    "make_multislice_mesh",
    "DEFAULT_RULES",
    "logical_spec",
    "logical_sharding",
    "tree_shardings",
    "shard_pytree",
]
