"""ZeRO-sharded optimizer benchmark (arXiv:2004.13336): capacity + wire.

Two legs, two halves of the claim:

**capacity** — BENCH_8B measured the v5e wall empirically: fp32 params
+ adamw moments eat ~9.4 GB of 16 GB, committing [4 layers, batch 2]
and OOMing six larger configs ([6,1] among them). This leg runs the
SAME full-size llama3-8b layer recipe at **[6,1]** — a strictly larger
config — with the optimizer state sharded 8 ways (train/zero.py,
rank 0's shard resident), takes a real fwd+bwd step through
``jit_grad_step`` plus the shard-local update, and reports the memory
ledger's ``peak_hbm_gb`` under the 16 GB chaos cap, next to the
analytic planner's verdicts (``plan(zero=8)``) for every claim. The
unsharded [6,1] "oom" verdict is anchored to BENCH_8B's empirical
boundary (the planner must agree); the sharded "fits" verdict is
measured here. The step runs at ``BENCH_ZERO_SEQ`` (default 256) with
dense attention — resident state, the binding constraint, does not
depend on seq; the seq-4096 capacity claim is the planner row.

**dataplane** — the bench_overlap worker harness (4 dp ranks, cpu
backend, L-layer MLP, hand-rolled deterministic adamw) runs the same
training two ways on two data planes each:

- ``allreduce`` / ``allreduce_hub``: bucketed allreduce (auto ring vs
  pinned hub), full update on every rank — the current path.
- ``zero`` / ``zero_hub``: reduce-scatter each bucket to its
  round-robin owner (``sync_sharded_async``), shard-local adamw,
  allgather weights.

The hub reduces allreduce and reducescatter contributions in the SAME
fp32 order, so the hub pair's loss gap must be EXACTLY 0.0 — the
sharded update is the same math, not merely close. The ring planes
reorder the accumulation (ring-order partial sums), so the auto pair
is held to < 1e-5; its job is the wire claim: measured bytes/step of
the zero leg ≤ the allreduce leg (the two ring hops move the same
2(n-1)/n·B the ring allreduce does, packed-RPC counters as witness).

Run: ``python bench_zero.py`` (writes BENCH_zero.json next to this
file). ``BENCH_ZERO_SKIP_CAPACITY=1`` runs the dataplane leg only.
"""

from __future__ import annotations

import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

WORLD = 4
LAYERS = 8
DIM = 256
BATCH = 64
STEPS = 3
BUCKET_BYTES = WORLD * DIM * DIM * 4  # world layers per bucket: balanced

ZERO_SHARD = 8       # capacity leg: 8-way optimizer sharding
CAPACITY_LAYERS = 6  # strictly larger than BENCH_8B's [4,2] boundary
CAPACITY_BATCH = 1


def _adamw_update(lr=1e-2, b1=0.9, b2=0.95, eps=1e-8, wd=0.0):
    """Hand-rolled deterministic adamw on numpy leaves: state is
    (t, m, v). Identical fp32 op order whether applied tree-wide
    (allreduce leg) or per owned leaf (zero legs)."""
    import numpy as np

    def update(grad, state, param):
        t, m, v = state
        t += 1
        m = b1 * m + (1.0 - b1) * grad
        v = b2 * v + (1.0 - b2) * grad * grad
        mhat = m / (1.0 - b1 ** t)
        vhat = v / (1.0 - b2 ** t)
        new_p = param - lr * (
            mhat / (np.sqrt(vhat) + eps) + wd * param
        )
        return new_p.astype(np.float32), (t, m, v)

    return update


def _member_class():
    import ray_tpu

    @ray_tpu.remote
    class Worker:
        """One dp rank of the dataplane leg (bench_overlap's MLP
        harness): numpy compute, cpu collective backend."""

        def setup(self, world, rank, group):
            import numpy as np

            import ray_tpu.collective as col

            col.init_collective_group(
                world, rank, backend="cpu", group_name=group, timeout_s=120
            )
            self._world = world
            self._rank = rank
            self._group = group
            r = np.random.default_rng(7)  # identical init on every rank
            self._params0 = {
                f"w{li}": (
                    r.normal(size=(DIM, DIM)) * (1.0 / np.sqrt(DIM))
                ).astype(np.float32)
                for li in range(LAYERS)
            }
            self._batch = np.random.default_rng(100 + rank).normal(
                size=(BATCH, DIM)
            ).astype(np.float32)
            return rank

        def _forward(self, params):
            import numpy as np

            acts = [self._batch]
            h = self._batch
            for li in range(LAYERS):
                h = np.tanh(h @ params[f"w{li}"])
                acts.append(h)
            return float(np.mean(h * h)), acts

        def _grads(self, params, acts):
            import numpy as np

            h_out = acts[-1]
            dh = 2.0 * h_out / h_out.size
            grads = {}
            for li in reversed(range(LAYERS)):
                dz = dh * (1.0 - acts[li + 1] ** 2)
                grads[f"w{li}"] = (acts[li].T @ dz).astype(np.float32)
                dh = dz @ params[f"w{li}"].T
            return grads

        def _wire_bytes(self, verbs):
            from ray_tpu.collective.flight_recorder import WIRE_BYTES

            total = 0.0
            for verb in verbs:
                total += WIRE_BYTES.value(
                    {
                        "group": self._group,
                        "verb": verb,
                        "dtype": "float32",
                    },
                    default=0.0,
                ) or 0.0
            return total

        def run_leg(self, mode: str):
            """mode ∈ {allreduce, zero} × {auto (ring), _hub}: the hub
            pair reduces in identical fp32 order (bitwise parity); the
            auto pair rides the ring planes (the wire comparison)."""
            import numpy as np

            from ray_tpu.collective.bucketer import GradBucketer
            from ray_tpu.train.zero import ZeroOptimizer

            algo = None if mode.endswith("_hub") else "auto"
            mode = mode.removesuffix("_hub")
            bucketer = GradBucketer(
                group_name=self._group,
                bucket_bytes=BUCKET_BYTES,
                algo=algo,
            )
            params = {k: v.copy() for k, v in self._params0.items()}
            update = _adamw_update()

            class _Opt:  # optax-shaped per-leaf init for ZeroOptimizer
                @staticmethod
                def init(leaf):
                    return (0, np.zeros_like(leaf), np.zeros_like(leaf))

            zo = None
            if mode != "allreduce":
                zo = ZeroOptimizer(_Opt(), params, self._rank, self._world)
            verbs = (
                ("allreduce",)
                if mode == "allreduce"
                else ("reducescatter", "allgather")
            )
            wire0 = self._wire_bytes(verbs)
            states = {k: _Opt.init(v) for k, v in params.items()}
            loss = None
            import time as _time

            t0 = _time.perf_counter()
            for _step in range(STEPS):
                loss, acts = self._forward(params)
                grads = self._grads(params, acts)
                if mode == "allreduce":
                    synced = bucketer.unflatten(
                        grads, bucketer.sync_async(grads).wait(
                            timeout_s=120
                        )
                    )
                    for k in params:
                        g = np.asarray(synced[k]) / self._world
                        params[k], states[k] = update(
                            g, states[k], params[k]
                        )
                else:
                    pending = bucketer.sync_sharded_async(grads)
                    owned = pending.wait(timeout_s=120)
                    updated = zo.apply(
                        owned,
                        params,
                        grad_scale=1.0 / self._world,
                        update_fn=lambda _k, g, st, p: update(g, st, p),
                    )
                    gathered = pending.allgather_updated(
                        updated, timeout_s=120
                    ).wait(timeout_s=120)
                    params = bucketer.zero_unflatten(params, gathered)
            dur = (_time.perf_counter() - t0) / STEPS
            plan = (
                bucketer.last_plan
                if mode == "allreduce"
                else bucketer.last_zero_plan
            )
            return {
                "loss": loss,
                "step_time_s": dur,
                "wire_bytes_per_step": (
                    self._wire_bytes(verbs) - wire0
                ) / STEPS,
                "buckets": len(plan),
                "algos": sorted(
                    {
                        getattr(b, "algo", None) or getattr(
                            b, "algo_rs", None
                        ) or "default"
                        for b in plan
                    }
                ),
                "opt_leaves_resident": (
                    LAYERS if mode == "allreduce" else len(zo.states)
                ),
            }

    return Worker


def dataplane_leg() -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=WORLD + 2)
    try:
        Worker = _member_class()
        workers = [Worker.remote() for _ in range(WORLD)]
        ray_tpu.get(
            [
                w.setup.remote(WORLD, i, "bench_zero")
                for i, w in enumerate(workers)
            ]
        )
        legs = {}
        for mode in ("allreduce", "zero", "allreduce_hub", "zero_hub"):
            outs = ray_tpu.get(
                [w.run_leg.remote(mode) for w in workers], timeout=600
            )
            legs[mode] = {
                "per_rank_loss": [o["loss"] for o in outs],
                "step_time_s": sum(o["step_time_s"] for o in outs)
                / len(outs),
                "wire_bytes_per_step": max(
                    o["wire_bytes_per_step"] for o in outs
                ),
                "buckets": outs[0]["buckets"],
                "algos": outs[0]["algos"],
                "opt_leaves_resident": [
                    o["opt_leaves_resident"] for o in outs
                ],
            }
    finally:
        ray_tpu.shutdown()

    ar, zr = legs["allreduce"], legs["zero"]
    ah, zh = legs["allreduce_hub"], legs["zero_hub"]
    hub_gap = max(
        abs(a - z)
        for a, z in zip(ah["per_rank_loss"], zh["per_rank_loss"])
    )
    ring_gap = max(
        abs(a - z)
        for a, z in zip(ar["per_rank_loss"], zr["per_rank_loss"])
    )
    wire_ratio = zr["wire_bytes_per_step"] / max(
        1.0, ar["wire_bytes_per_step"]
    )
    out = {
        "world": WORLD,
        "model": {"layers": LAYERS, "dim": DIM, "batch": BATCH},
        "bucket_bytes": BUCKET_BYTES,
        "steps": STEPS,
        "legs": legs,
        # Hub plane reduces allreduce and reducescatter in the same
        # fp32 order: the sharded update must be EXACTLY the same math.
        "loss_gap_hub": hub_gap,
        "loss_parity_exact": bool(hub_gap == 0.0),
        "loss_gap_ring": ring_gap,
        "wire_ratio_zero_vs_allreduce": round(wire_ratio, 4),
        "wire_le_allreduce": bool(
            zr["wire_bytes_per_step"] <= ar["wire_bytes_per_step"]
        ),
        # Each rank keeps optimizer state for ~1/world of the leaves.
        "opt_leaves_sharded": zr["opt_leaves_resident"],
        "opt_leaves_replicated": ar["opt_leaves_resident"],
    }
    assert out["loss_parity_exact"], (
        f"sharded (hub) loss diverged from allreduce by {hub_gap}"
    )
    assert ring_gap < 1e-5, (
        f"sharded (ring) loss diverged from allreduce by {ring_gap}"
    )
    assert out["wire_le_allreduce"], (
        f"sharded wire bytes/step {zr['wire_bytes_per_step']} > "
        f"allreduce {ar['wire_bytes_per_step']}"
    )
    return out


def planner_block(measured_seq: int, worst_divide: int) -> dict:
    """Analytic verdicts for every capacity claim, all of which must
    match their empirical anchor: unsharded [6,1]@4096 ooms (BENCH_8B
    measured it), zero=8 [6,1] fits at both the measured seq and the
    canonical 4096 — INCLUDING the worst-loaded owner (leaf-granular
    round-robin over the flagship's ~12 layer-stacked leaves is
    uneven; ``worst_divide`` is the effective optimizer divide of the
    heaviest shard, always < ZERO_SHARD) — and BENCH_8B's committed
    [4,2] still fits."""
    import dataclasses as dc

    from ray_tpu.models import PRESETS
    from ray_tpu.train.memory import plan

    cfg = dc.replace(
        PRESETS["llama3_8b"],
        n_layers=CAPACITY_LAYERS,
        vocab_size=8192,
        attn_impl="flash",
        remat="full",
    )
    cfg42 = dc.replace(cfg, n_layers=4)
    rows = []
    for label, c, batch, seq, zero, empirical in (
        ("[6,1] replicated adamw, seq 4096", cfg, 1, 4096, 1, "oom"),
        (f"[6,1] zero={ZERO_SHARD}, seq {measured_seq}", cfg, 1,
         measured_seq, ZERO_SHARD, "fits"),
        (f"[6,1] zero={ZERO_SHARD}, seq 4096", cfg, 1, 4096,
         ZERO_SHARD, "fits"),
        (f"[6,1] zero={ZERO_SHARD} WORST owner (effective divide "
         f"{worst_divide}), seq 4096", cfg, 1, 4096, worst_divide,
         "fits"),
        ("[4,2] replicated adamw, seq 4096 (BENCH_8B committed)",
         cfg42, 2, 4096, 1, "fits"),
    ):
        p = plan(c, batch, seq, mu_dtype="bfloat16", hbm_gb=16.0,
                 zero=zero)
        predicted = "fits" if p.fits else "oom"
        rows.append(
            {
                "config": label,
                "predicted_gb": round(p.total_gb, 2),
                "optimizer_gb": round(p.optimizer_bytes / 2**30, 2),
                "predicted": predicted,
                "empirical": empirical,
                "empirical_source": (
                    "BENCH_8B boundary" if zero == 1 else "this run"
                ),
                "match": predicted == empirical,
            }
        )
    return {
        "model": "analytic (ray_tpu.train.memory.plan, zero= divides "
                 "the adamw state): fp32 params + sharded adamw + fp32 "
                 "grads + remat-full activations + chunked-CE logits "
                 "vs 16 GiB minus XLA reserve",
        "hbm_gb": 16.0,
        "configs": rows,
        "all_match": all(r["match"] for r in rows),
    }


def capacity_leg() -> dict:
    """Real [6,1] llama3-8b layers with the optimizer sharded 8 ways:
    rank 0's resident set (full params + 1/8 adamw), one real fwd+bwd
    step + shard-local update, memory ledger peak under the 16 GB
    chaos cap."""
    import dataclasses as dc
    import time

    os.environ.setdefault("RAY_TPU_FAKE_HBM_GB", "16")
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import PRESETS
    from ray_tpu.runtime import memory as rmem
    from ray_tpu.train.step import (
        init_zero_train_state,
        jit_grad_step,
        make_optimizer,
    )

    seq = int(os.environ.get("BENCH_ZERO_SEQ", "256"))
    cfg = dc.replace(
        PRESETS["llama3_8b"],
        n_layers=CAPACITY_LAYERS,
        vocab_size=8192,
        # dense attention: the pallas flash kernel interprets (slowly)
        # on the CPU twin; resident state — the binding constraint —
        # is attention-impl-independent.
        attn_impl="dense",
        remat="full",
    )
    opt = make_optimizer(total_steps=1000, mu_dtype=jnp.bfloat16,
                         grad_clip=1.0)
    t0 = time.perf_counter()
    params, zo = init_zero_train_state(
        jax.random.key(0), cfg, opt, rank=0, world=ZERO_SHARD
    )
    init_s = time.perf_counter() - t0
    grad_step = jit_grad_step(cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (CAPACITY_BATCH, seq + 1), 0, cfg.vocab_size
    )
    t1 = time.perf_counter()
    metrics, grads = grad_step(params, {"tokens": tokens})
    loss = float(metrics["loss"])
    # Shard-local update on the owned leaves (reduce-scatter is a
    # no-op at dp=1; ownership math runs at world=ZERO_SHARD exactly
    # as each pod rank would).
    leaf_grads = zo.leaf_map(grads)
    owned = {k: leaf_grads[k] for k in zo.owned_keys()}
    updated = zo.apply(owned, params)
    step_s = time.perf_counter() - t1
    del updated, grads, leaf_grads, owned
    samp = rmem.sample(emit=False) or {}
    hbm = samp.get("hbm") or {}
    peak = hbm.get("peak_bytes") or hbm.get("used_bytes") or 0
    n_owned = len(zo.owned_keys())
    n_total = len(zo.keys)
    import numpy as _np

    leaf_bytes = {
        k: _np.asarray(v).nbytes for k, v in zo.leaf_map(params).items()
    }
    params_gb = sum(leaf_bytes.values()) / 2**30
    shard_gb = zo.shard_bytes() / 2**30
    # Per-owner optimizer bytes (bf16 mu = 0.5x + fp32 nu = 1.0x the
    # fp32 leaf): leaf-granular round-robin over ~12 layer-stacked
    # leaves is UNEVEN — the capacity claim must hold for the heaviest
    # owner, not the rank this process happens to be.
    per_owner = [0] * ZERO_SHARD
    for k, owner in zo.owners.items():
        per_owner[owner] += int(1.5 * leaf_bytes[k])
    full_opt_bytes = sum(per_owner)
    max_shard_bytes = max(per_owner)
    full_opt_gb = full_opt_bytes / 2**30
    worst_divide = max(1, full_opt_bytes // max(1, max_shard_bytes))
    return {
        "config": [CAPACITY_LAYERS, CAPACITY_BATCH],
        "seq": seq,
        "params": int(cfg.num_params()),
        "zero_shard": ZERO_SHARD,
        "loss": round(loss, 3),
        "init_s": round(init_s, 1),
        "step_s": round(step_s, 1),
        "opt_leaves_owned": f"{n_owned}/{n_total}",
        "params_gb": round(params_gb, 2),
        "opt_shard_gb": round(shard_gb, 2),
        "opt_shard_max_gb": round(max_shard_bytes / 2**30, 2),
        "opt_shard_worst_divide": int(worst_divide),
        "opt_replicated_gb": round(full_opt_gb, 2),
        "resident_state_gb": round(params_gb + shard_gb, 2),
        "resident_state_worst_gb": round(
            params_gb + max_shard_bytes / 2**30, 2
        ),
        "resident_state_replicated_gb": round(
            params_gb + full_opt_gb, 2
        ),
        "peak_hbm_gb": round(peak / 2**30, 2) if peak else None,
        "peak_hbm_source": hbm.get("source"),
        "hbm_cap_gb": 16.0,
        "fits_16gb": bool(peak and peak < 16 * 2**30),
    }


def main() -> dict:
    result = {"bench": "zero", "metric": "zero_sharded_optimizer"}
    if os.environ.get("BENCH_ZERO_SKIP_CAPACITY") != "1":
        result["capacity"] = capacity_leg()
        result["planner"] = planner_block(
            result["capacity"]["seq"],
            result["capacity"]["opt_shard_worst_divide"],
        )
        assert result["capacity"]["fits_16gb"], result["capacity"]
        assert result["planner"]["all_match"], result["planner"]
        result["larger_config_fits"] = bool(
            result["capacity"]["fits_16gb"]
            and result["planner"]["all_match"]
        )
    result["dataplane"] = dataplane_leg()
    result["ok"] = True
    return result


if __name__ == "__main__":
    out = main()
    path = os.environ.get("BENCH_ZERO_OUT") or os.path.join(
        os.path.dirname(__file__), "BENCH_zero.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    print(f"wrote {path}")
