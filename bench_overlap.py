"""Bucketed gradient-sync overlap benchmark: serial vs overlapped step.

Runs the SAME data-parallel train step two ways on the 8-device dryrun
configuration (4 worker processes, cpu collective backend — the
backend whose RPC data plane runs on a background loop thread, so the
overlap is real wall-clock concurrency, not accounting):

- **serial**: full layer-by-layer backward in the ``compute`` phase,
  then every gradient bucket allreduced (and joined) in the
  ``collective`` phase — the pre-overlap step shape whose collective
  time is fully exposed.
- **overlapped**: each layer's gradients are streamed into the
  bucketer AS BACKWARD PRODUCES THEM (reverse-layer order); full
  buckets dispatch immediately via ``allreduce_async`` and run while
  the remaining backward compute proceeds; the ``collective`` phase
  only joins the tail.

Per step each worker measures the phase split with the train
telemetry's StepTimer and the comm-exposure attribution
(flight-recorder op intervals ∩ compute phase), exactly the math the
``ray_tpu_train_comm_exposed_ratio`` gauge uses. Headline asserts:

- the overlapped path cuts ``comm_exposed_ratio`` by >= 30% vs serial,
- at equal loss (same reductions, different schedule; gap < 1e-5).

Run: ``python bench_overlap.py`` (writes BENCH_overlap.json next to
this file).
"""

from __future__ import annotations

import json
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

WORLD = 4
LAYERS = 8
DIM = 512
BATCH = 256
STEPS = 3  # measured steps (after 1 warmup)
BUCKET_BYTES = DIM * DIM * 4  # one layer per bucket


def _member_class():
    import ray_tpu

    @ray_tpu.remote
    class Worker:
        """One dp rank: an L-layer tanh MLP in numpy (host compute —
        backward really runs on the worker's main thread while bucket
        allreduces progress on the runtime loop thread)."""

        def setup(self, world, rank, group):
            import numpy as np

            import ray_tpu.collective as col

            col.init_collective_group(
                world, rank, backend="cpu", group_name=group, timeout_s=120
            )
            self._world = world
            self._rank = rank
            self._group = group
            r = np.random.default_rng(7)  # identical init on every rank
            self._params0 = [
                (r.normal(size=(DIM, DIM)) * (1.0 / np.sqrt(DIM))).astype(
                    np.float32
                )
                for _ in range(LAYERS)
            ]
            self._batch = np.random.default_rng(100 + rank).normal(
                size=(BATCH, DIM)
            ).astype(np.float32)
            return rank

        def _forward(self, params):
            import numpy as np

            acts = [self._batch]
            h = self._batch
            for w in params:
                h = np.tanh(h @ w)
                acts.append(h)
            loss = float(np.mean(h * h))
            return loss, acts

        def _layer_grads(self, params, acts):
            """Generator yielding (layer_index, dW) in REVERSE layer
            order — the order backward produces gradients."""
            import numpy as np

            h_out = acts[-1]
            dh = 2.0 * h_out / h_out.size
            for li in reversed(range(LAYERS)):
                dz = dh * (1.0 - acts[li + 1] ** 2)
                dw = acts[li].T @ dz
                dh = dz @ params[li].T
                yield li, dw.astype(np.float32)

        def run_leg(self, overlapped: bool):
            """STEPS measured steps; returns per-step telemetry and the
            final loss. Both legs apply the identical mean-gradient SGD
            update — the overlap changes the schedule, not the math."""
            import numpy as np

            from ray_tpu.collective import flight_recorder
            from ray_tpu.collective.bucketer import GradBucketer
            from ray_tpu.train import telemetry

            bucketer = GradBucketer(
                group_name=self._group, bucket_bytes=BUCKET_BYTES
            )
            params = [w.copy() for w in self._params0]
            flops_per_step = 6 * BATCH * DIM * DIM * LAYERS
            rows = []
            loss = None
            for step in range(STEPS + 1):
                flight_recorder.take_op_intervals()  # drain stale ops
                timer = telemetry.StepTimer(flops_per_step)
                grads: list = [None] * LAYERS
                stream = bucketer.stream()
                with timer.phase("compute"):
                    loss, acts = self._forward(params)
                    for li, dw in self._layer_grads(params, acts):
                        grads[li] = dw
                        if overlapped:
                            # Eager issue: the bucket's allreduce runs
                            # behind the remaining backward layers.
                            stream.add(f"w{li}", dw)
                if not overlapped:
                    for li in reversed(range(LAYERS)):
                        stream.add(f"w{li}", grads[li])
                with timer.phase("collective"):
                    synced = stream.finish().wait(timeout_s=120)
                with timer.phase("compute"):
                    for li in range(LAYERS):
                        params[li] = params[li] - 0.1 * (
                            synced[f"w{li}"] / self._world
                        )
                dur = timer.elapsed()
                exposed, overlapped_s = telemetry.comm_attribution(
                    timer.start, timer.start + dur, timer._events
                )
                if step == 0:
                    continue  # warmup (connections, allocator)
                rows.append(
                    {
                        "step_time_s": dur,
                        "comm_exposed_s": exposed,
                        "comm_overlapped_s": overlapped_s,
                        "comm_exposed_ratio": exposed / dur,
                        "mfu": telemetry.compute_mfu(flops_per_step, dur)
                        or 0.0,
                    }
                )
            return {"rows": rows, "loss": loss}

    return Worker


def _mean(rows, key):
    return sum(r[key] for r in rows) / max(1, len(rows))


def main() -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=WORLD + 2)
    try:
        Worker = _member_class()
        workers = [Worker.remote() for _ in range(WORLD)]
        ray_tpu.get(
            [
                w.setup.remote(WORLD, i, "bench_overlap")
                for i, w in enumerate(workers)
            ]
        )
        legs = {}
        for name, overlapped in (("serial", False), ("overlapped", True)):
            outs = ray_tpu.get(
                [w.run_leg.remote(overlapped) for w in workers],
                timeout=600,
            )
            rows = [r for o in outs for r in o["rows"]]
            legs[name] = {
                # Each rank's loss is on its own batch; the leg's loss
                # is the dp mean (what a global eval would report).
                "loss": sum(o["loss"] for o in outs) / len(outs),
                "per_rank_loss": [o["loss"] for o in outs],
                "per_step": outs[0]["rows"],
                "step_time_s": _mean(rows, "step_time_s"),
                "comm_exposed_s": _mean(rows, "comm_exposed_s"),
                "comm_overlapped_s": _mean(rows, "comm_overlapped_s"),
                "comm_exposed_ratio": _mean(rows, "comm_exposed_ratio"),
                "mfu": _mean(rows, "mfu"),
            }
    finally:
        ray_tpu.shutdown()

    serial, overl = legs["serial"], legs["overlapped"]
    ratio_cut = 1.0 - (
        overl["comm_exposed_ratio"] / max(1e-9, serial["comm_exposed_ratio"])
    )
    # Parity is per rank: the same rank saw the same batches and must
    # land on the same loss under either schedule.
    loss_gap = max(
        abs(s - o)
        for s, o in zip(serial["per_rank_loss"], overl["per_rank_loss"])
    )
    result = {
        "bench": "overlap",
        "world": WORLD,
        "model": {"layers": LAYERS, "dim": DIM, "batch": BATCH},
        "bucket_bytes": BUCKET_BYTES,
        "steps": STEPS,
        "serial": serial,
        "overlapped": overl,
        "exposed_ratio_cut": round(ratio_cut, 4),
        "exposed_ratio_cut_ge_030": bool(ratio_cut >= 0.30),
        "loss_gap": loss_gap,
        "loss_parity_lt_1e5": bool(loss_gap < 1e-5),
        "step_speedup": round(
            serial["step_time_s"] / max(1e-9, overl["step_time_s"]), 4
        ),
    }
    assert result["loss_parity_lt_1e5"], (
        f"overlapped loss diverged from serial by {loss_gap}"
    )
    assert result["exposed_ratio_cut_ge_030"], (
        f"overlap cut comm_exposed_ratio by only {ratio_cut:.1%} "
        f"(serial {serial['comm_exposed_ratio']:.4f} -> overlapped "
        f"{overl['comm_exposed_ratio']:.4f}); >= 30% required"
    )
    return result


if __name__ == "__main__":
    out = main()
    path = os.path.join(os.path.dirname(__file__), "BENCH_overlap.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    print(f"wrote {path}")
