// Native token data loader: the C++ input pipeline for TPU training.
//
// Mirrors the role of the reference's native data path (Arrow blocks +
// C++ scanners under ray.data; the directive's "data-loader" component):
// a memory-mapped binary token file is sliced into fixed-length windows,
// shuffled by a seeded Fisher-Yates permutation, gathered into dense
// [batch, seq+1] uint32 batches, and (optionally) double-buffered by a
// background thread so the host gather overlaps device compute.
//
// File format: a flat array of little-endian uint16 or uint32 token ids
// (the standard .bin corpus dump). Sharding for data parallelism is a
// (rank, world) stride over the shuffled window permutation.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cerrno>

#include <condition_variable>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Loader {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t file_bytes = 0;
  int dtype_bytes = 4;       // 2 (uint16) or 4 (uint32)
  uint64_t n_tokens = 0;
  uint64_t window = 0;       // tokens per sample (seq + 1)
  uint64_t n_windows = 0;
  std::vector<uint64_t> perm;

  // Prefetch state (one background gather in flight).
  std::thread worker;
  std::mutex mu;
  std::condition_variable cv;
  std::vector<uint32_t> ready_buf;
  uint64_t cursor = 0;       // next permutation index to gather
  uint64_t batch = 0;
  uint64_t rank = 0, world_size = 1;
  bool buf_full = false;
  bool stop = false;
  bool prefetching = false;
};

inline uint32_t token_at(const Loader* L, uint64_t i) {
  if (L->dtype_bytes == 2) {
    uint16_t v;
    memcpy(&v, L->base + i * 2, 2);
    return v;
  }
  uint32_t v;
  memcpy(&v, L->base + i * 4, 4);
  return v;
}

// Gather one batch at permutation offset `start` (strided by the shard),
// returning rows actually filled (< batch only at epoch end).
uint64_t gather(Loader* L, uint64_t start, uint64_t batch, uint32_t* out) {
  uint64_t rows = 0;
  for (uint64_t b = 0; b < batch; b++) {
    uint64_t p = (start + b) * L->world_size + L->rank;
    if (p >= L->n_windows) break;
    uint64_t w = L->perm[p];
    const uint64_t off = w * L->window;
    uint32_t* dst = out + b * L->window;
    if (L->dtype_bytes == 4) {
      memcpy(dst, L->base + off * 4, L->window * 4);
    } else {
      for (uint64_t t = 0; t < L->window; t++) dst[t] = token_at(L, off + t);
    }
    rows++;
  }
  return rows;
}

void prefetch_loop(Loader* L) {
  std::unique_lock<std::mutex> lk(L->mu);
  while (!L->stop) {
    if (L->buf_full) {
      L->cv.wait(lk);
      continue;
    }
    uint64_t start = L->cursor;
    uint64_t batch = L->batch;
    lk.unlock();
    std::vector<uint32_t> buf(batch * L->window);
    uint64_t rows = gather(L, start, batch, buf.data());
    buf.resize(rows * L->window);
    lk.lock();
    if (L->stop) break;
    L->ready_buf = std::move(buf);
    L->buf_full = true;
    L->cursor += batch;
    L->cv.notify_all();
    if (rows == 0) {
      // Epoch exhausted: park until the consumer takes the empty
      // sentinel and stops this prefetch run.
      while (!L->stop && L->buf_full) L->cv.wait(lk);
    }
  }
}

}  // namespace

extern "C" {

// Open a token file. dtype_bytes: 2 or 4. window = seq_len + 1.
// Returns an opaque handle or null.
void* dl_open(const char* path, int dtype_bytes, uint64_t window) {
  if ((dtype_bytes != 2 && dtype_bytes != 4) || window == 0) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  madvise(mem, st.st_size, MADV_WILLNEED);
  Loader* L = new Loader;
  L->fd = fd;
  L->base = static_cast<const uint8_t*>(mem);
  L->file_bytes = st.st_size;
  L->dtype_bytes = dtype_bytes;
  L->n_tokens = st.st_size / dtype_bytes;
  L->window = window;
  L->n_windows = L->n_tokens / window;
  L->perm.resize(L->n_windows);
  for (uint64_t i = 0; i < L->n_windows; i++) L->perm[i] = i;
  return L;
}

uint64_t dl_num_windows(void* handle) {
  return static_cast<Loader*>(handle)->n_windows;
}

// Seeded Fisher-Yates shuffle of the window permutation (one epoch).
// splitmix64 PRNG: deterministic across platforms. Refused (-EBUSY)
// while a prefetch thread is running: gather() reads perm unlocked.
int dl_shuffle(void* handle, uint64_t seed) {
  Loader* L = static_cast<Loader*>(handle);
  // Hold the mutex for the WHOLE shuffle: a concurrent
  // dl_prefetch_start (ctypes releases the GIL) then blocks here until
  // perm is consistent, instead of racing gather() against the swaps.
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->prefetching) return -EBUSY;
  uint64_t x = seed + 0x9E3779B97F4A7C15ULL;
  auto next = [&x]() {
    x += 0x9E3779B97F4A7C15ULL;
    uint64_t z = x;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  };
  for (uint64_t i = L->n_windows; i > 1; i--) {
    uint64_t j = next() % i;
    std::swap(L->perm[i - 1], L->perm[j]);
  }
  return 0;
}

// Synchronous gather of `batch` windows starting at shard-local
// permutation offset `start`; fills out[batch * window] (uint32).
// Returns rows filled.
uint64_t dl_fill(void* handle, uint64_t start, uint64_t batch,
                 uint32_t* out) {
  return gather(static_cast<Loader*>(handle), start, batch, out);
}

// Configure the shard (data parallelism): this loader sees permutation
// entries rank, rank+world, rank+2*world, ... Refused (-EBUSY) while
// prefetching (gather() reads these unlocked).
int dl_set_shard(void* handle, uint64_t rank, uint64_t world_size) {
  Loader* L = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->prefetching) return -EBUSY;
  L->rank = rank;
  L->world_size = world_size ? world_size : 1;
  return 0;
}

// ---- background prefetch (double buffering) -------------------------
int dl_prefetch_start(void* handle, uint64_t batch) {
  Loader* L = static_cast<Loader*>(handle);
  std::lock_guard<std::mutex> lk(L->mu);
  if (L->prefetching) return -EBUSY;
  L->batch = batch;
  L->cursor = 0;
  L->buf_full = false;
  L->stop = false;
  L->prefetching = true;
  L->worker = std::thread(prefetch_loop, L);
  return 0;
}

// Blocks until the next prefetched batch is ready; copies it into
// out[batch * window] and wakes the worker for the next one.
// Returns rows filled (0 = epoch exhausted).
uint64_t dl_next(void* handle, uint32_t* out) {
  Loader* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->cv.wait(lk, [L] { return L->buf_full || L->stop; });
  if (L->stop) return 0;
  uint64_t rows = L->ready_buf.size() / L->window;
  memcpy(out, L->ready_buf.data(), L->ready_buf.size() * 4);
  L->buf_full = false;
  L->cv.notify_all();
  return rows;
}

void dl_prefetch_stop(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
    L->cv.notify_all();
  }
  if (L->worker.joinable()) L->worker.join();
  std::lock_guard<std::mutex> lk(L->mu);
  L->prefetching = false;
}

void dl_close(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  if (L->prefetching) dl_prefetch_stop(L);
  munmap(const_cast<uint8_t*>(L->base), L->file_bytes);
  close(L->fd);
  delete L;
}

}  // extern "C"
