// Shared-memory object pool: the C++ core of the per-node object store.
//
// TPU-native equivalent of the reference's plasma store (reference:
// src/ray/object_manager/plasma/store.h:55, plasma_allocator.h + dlmalloc,
// eviction_policy.h LRU, obj_lifecycle_mgr.h). Design difference: plasma
// is a daemon brokering mmap fds over a unix socket; here the pool is one
// mmap'd file in /dev/shm that every process on the node maps directly,
// with a process-shared mutex guarding a fixed open-addressing object
// table and a first-fit free-list heap. create/seal/get/release/delete
// plus LRU eviction of sealed, unreferenced objects when an allocation
// does not fit. No daemon, no fd-passing (fling.cc) needed: POSIX shm on
// Linux is just files.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <cerrno>
#include <cstdio>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055504F4F4CULL;  // "RTPUPOOL"
constexpr uint32_t kIdLen = 20;                     // ObjectID bytes
constexpr uint64_t kAlign = 64;

inline uint64_t aligned(uint64_t n) { return (n + kAlign - 1) / kAlign * kAlign; }

struct Slot {
  uint8_t id[kIdLen];
  // 0 empty, 1 creating, 2 sealed, 3 tombstone,
  // 4 zombie: deleted-but-pinned — unlinked from lookups (get/contains
  // miss, the id is reusable) but the heap block stays allocated until
  // the last reader releases (plasma semantics: delete defers the free,
  // never invalidates memory a client still maps).
  uint8_t state;
  uint8_t pad[3];
  uint32_t refcount;
  uint64_t offset;  // heap offset of payload
  uint64_t size;
  uint64_t lru;  // last-touch tick
};

// Free-list node, stored inside the heap itself. While a block is
// ALLOCATED, `next` holds the owning slot's index instead (so a release
// keyed by payload offset finds its slot in O(1) — see shm_release_at).
struct Block {
  uint64_t size;   // payload bytes of this block (excluding header)
  uint64_t next;   // free: heap offset of next free block (0 = end)
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // total file size
  uint64_t heap_off;      // start of heap region
  uint64_t heap_size;
  uint64_t free_head;     // heap offset of first free block, 0 = none
  uint64_t lru_clock;
  uint64_t used_bytes;
  uint32_t num_slots;
  uint32_t pad;
  pthread_mutex_t mutex;  // PTHREAD_PROCESS_SHARED
  // Slot table follows, then heap.
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t size;
  Header* hdr;
  Slot* slots;
};

inline Block* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<Block*>(h->base + h->hdr->heap_off + off);
}

uint64_t hash_id(const uint8_t* id) {
  uint64_t x = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) { x ^= id[i]; x *= 1099511628211ULL; }
  return x;
}

Slot* find_slot(Handle* h, const uint8_t* id, bool for_insert) {
  Header* hdr = h->hdr;
  uint64_t n = hdr->num_slots;
  uint64_t i = hash_id(id) % n;
  Slot* first_tomb = nullptr;
  for (uint64_t probes = 0; probes < n; probes++, i = (i + 1) % n) {
    Slot* s = &h->slots[i];
    if (s->state == 0) return for_insert ? (first_tomb ? first_tomb : s) : nullptr;
    if (s->state == 3) { if (for_insert && !first_tomb) first_tomb = s; continue; }
    if (s->state == 4) continue;  // zombie: unlinked, slot still occupied
    if (memcmp(s->id, id, kIdLen) == 0) return s;
  }
  return first_tomb;  // table full of tombstones/entries
}

// Heap: singly-linked first-fit free list. Offsets are relative to
// heap_off; a block's payload starts at off + sizeof(Block).
uint64_t heap_alloc(Handle* h, uint64_t want) {
  want = aligned(want);
  Header* hdr = h->hdr;
  uint64_t prev = 0;
  uint64_t cur = hdr->free_head;
  while (cur) {
    Block* b = block_at(h, cur);
    if (b->size >= want) {
      uint64_t remain = b->size - want;
      if (remain > sizeof(Block) + kAlign) {
        // split: tail of this block becomes a new free block
        uint64_t tail_off = cur + sizeof(Block) + want;
        Block* tail = block_at(h, tail_off);
        tail->size = remain - sizeof(Block);
        tail->next = b->next;
        b->size = want;
        if (prev) block_at(h, prev)->next = tail_off; else hdr->free_head = tail_off;
      } else {
        if (prev) block_at(h, prev)->next = b->next; else hdr->free_head = b->next;
      }
      hdr->used_bytes += b->size + sizeof(Block);
      return cur + sizeof(Block);  // payload offset
    }
    prev = cur;
    cur = b->next;
  }
  return UINT64_MAX;
}

void heap_free(Handle* h, uint64_t payload_off) {
  Header* hdr = h->hdr;
  uint64_t off = payload_off - sizeof(Block);
  Block* b = block_at(h, off);
  hdr->used_bytes -= b->size + sizeof(Block);
  // insert sorted by offset, coalesce neighbors
  uint64_t prev = 0, cur = hdr->free_head;
  while (cur && cur < off) { prev = cur; cur = block_at(h, cur)->next; }
  b->next = cur;
  if (prev) block_at(h, prev)->next = off; else hdr->free_head = off;
  // coalesce with next
  if (cur && off + sizeof(Block) + b->size == cur) {
    Block* nb = block_at(h, cur);
    b->size += sizeof(Block) + nb->size;
    b->next = nb->next;
  }
  // coalesce with prev
  if (prev) {
    Block* pb = block_at(h, prev);
    if (prev + sizeof(Block) + pb->size == off) {
      pb->size += sizeof(Block) + b->size;
      pb->next = b->next;
    }
  }
}

// Evict the least-recently-used sealed object with refcount 0.
// Returns true if something was evicted.
bool evict_one(Handle* h) {
  Header* hdr = h->hdr;
  Slot* victim = nullptr;
  for (uint32_t i = 0; i < hdr->num_slots; i++) {
    Slot* s = &h->slots[i];
    if (s->state == 2 && s->refcount == 0) {
      if (!victim || s->lru < victim->lru) victim = s;
    }
  }
  if (!victim) return false;
  heap_free(h, victim->offset);
  victim->state = 3;  // tombstone
  return true;
}

class MutexGuard {
 public:
  explicit MutexGuard(pthread_mutex_t* m) : m_(m) {
    int rc = pthread_mutex_lock(m_);
    if (rc == EOWNERDEAD) pthread_mutex_consistent(m_);
  }
  ~MutexGuard() { pthread_mutex_unlock(m_); }
 private:
  pthread_mutex_t* m_;
};

}  // namespace

extern "C" {

// Create the pool file (head/daemon side). Returns 0 on success.
int shm_pool_create(const char* path, uint64_t capacity, uint32_t num_slots) {
  uint64_t slots_off = aligned(sizeof(Header));
  uint64_t heap_off = aligned(slots_off + num_slots * sizeof(Slot));
  if (capacity < heap_off + kAlign * 16) return -EINVAL;
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return -errno;
  if (ftruncate(fd, (off_t)capacity) != 0) { int e = errno; close(fd); unlink(path); return -e; }
  void* mem = mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { int e = errno; close(fd); unlink(path); return -e; }
  Header* hdr = static_cast<Header*>(mem);
  memset(hdr, 0, heap_off);
  hdr->capacity = capacity;
  hdr->heap_off = heap_off;
  hdr->heap_size = capacity - heap_off;
  hdr->num_slots = num_slots;
  hdr->lru_clock = 1;
  hdr->used_bytes = 0;
  // one big free block at offset kAlign (0 is reserved: "no block")
  Block* first = reinterpret_cast<Block*>(static_cast<uint8_t*>(mem) + heap_off + kAlign);
  first->size = hdr->heap_size - kAlign - sizeof(Block);
  first->next = 0;
  hdr->free_head = kAlign;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  hdr->magic = kMagic;
  msync(mem, heap_off, MS_SYNC);
  munmap(mem, capacity);
  close(fd);
  return 0;
}

// Open an existing pool. Returns an opaque handle pointer, or null.
void* shm_pool_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Header* hdr = static_cast<Header*>(mem);
  if (hdr->magic != kMagic) { munmap(mem, st.st_size); close(fd); return nullptr; }
  Handle* h = new Handle;
  h->fd = fd;
  h->base = static_cast<uint8_t*>(mem);
  h->size = st.st_size;
  h->hdr = hdr;
  h->slots = reinterpret_cast<Slot*>(h->base + aligned(sizeof(Header)));
  return h;
}

void shm_pool_close(void* handle) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->base, h->size);
  close(h->fd);
  delete h;
}

// Base pointer of the mapping (so Python can mmap-slice payloads itself).
uint8_t* shm_pool_base(void* handle) { return static_cast<Handle*>(handle)->base; }
uint64_t shm_pool_capacity(void* handle) { return static_cast<Handle*>(handle)->hdr->capacity; }
uint64_t shm_pool_used(void* handle) { return static_cast<Handle*>(handle)->hdr->used_bytes; }

// Create an object of `size` bytes. On success returns 0 and writes the
// absolute byte offset of the payload into *out_off. -EEXIST if the id
// already exists, -ENOMEM if it cannot fit even after eviction.
int shm_create(void* handle, const uint8_t* id, uint64_t size, uint64_t* out_off) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, /*for_insert=*/false);
  if (s && (s->state == 1 || s->state == 2)) return -EEXIST;
  uint64_t payload;
  while ((payload = heap_alloc(h, size ? size : 1)) == UINT64_MAX) {
    if (!evict_one(h)) return -ENOMEM;
  }
  s = find_slot(h, id, /*for_insert=*/true);
  if (!s) { heap_free(h, payload); return -ENOSPC; }
  memcpy(s->id, id, kIdLen);
  s->state = 1;
  s->refcount = 1;  // creator holds a ref until seal+release
  s->offset = payload;
  s->size = size;
  s->lru = ++h->hdr->lru_clock;
  // Bind block → slot for offset-keyed release.
  block_at(h, payload - sizeof(Block))->next = (uint64_t)(s - h->slots);
  *out_off = h->hdr->heap_off + payload;
  return 0;
}

int shm_seal(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state != 1) return -ENOENT;
  s->state = 2;
  s->refcount = 0;
  s->lru = ++h->hdr->lru_clock;
  return 0;
}

// Pin + locate a sealed object. Returns 0 and fills offset/size.
int shm_get(void* handle, const uint8_t* id, uint64_t* out_off, uint64_t* out_size) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state != 2) return -ENOENT;
  s->refcount++;
  s->lru = ++h->hdr->lru_clock;
  *out_off = h->hdr->heap_off + s->offset;
  *out_size = s->size;
  return 0;
}

int shm_contains(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  return (s && s->state == 2) ? 1 : 0;
}

int shm_release(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state != 2) return -ENOENT;
  if (s->refcount > 0) s->refcount--;
  return 0;
}

int shm_delete(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state == 0 || s->state == 3) return -ENOENT;
  if (s->refcount > 0) {
    // Pinned (sealed readers, or a creator mid-memcpy on state 1):
    // unlink the id now (subsequent get/contains miss, the id may be
    // re-created) and free the block when the last holder releases —
    // never free memory another process is still writing or reading.
    s->state = 4;
    return 0;
  }
  heap_free(h, s->offset);
  s->state = 3;
  return 0;
}

// Release keyed by the payload's ABSOLUTE offset (what shm_get returned).
// Unlike release-by-id this stays correct when the id was deleted and
// re-created while this reader still pinned the OLD allocation: the
// offset identifies the allocation, and the block header carries its
// owning slot index.
int shm_release_at(void* handle, uint64_t abs_off) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Header* hdr = h->hdr;
  if (abs_off < hdr->heap_off + sizeof(Block)) return -EINVAL;
  uint64_t payload = abs_off - hdr->heap_off;
  Block* b = block_at(h, payload - sizeof(Block));
  uint64_t idx = b->next;
  if (idx >= hdr->num_slots) return -ENOENT;
  Slot* s = &h->slots[idx];
  if (s->offset != payload || (s->state != 2 && s->state != 4)) return -ENOENT;
  if (s->refcount > 0) s->refcount--;
  if (s->state == 4 && s->refcount == 0) {
    heap_free(h, s->offset);
    s->state = 3;
  }
  return 0;
}

// Scan sealed, unpinned objects (spill candidates). Fills up to
// `max_entries` of (id, size, lru) triples; returns the count. The spill
// loop ranks by lru ascending and moves cold objects to disk before the
// allocator's LRU eviction would drop them.
int shm_pool_scan(void* handle, uint8_t* out_ids, uint64_t* out_sizes,
                  uint64_t* out_lru, uint32_t max_entries) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  uint32_t n = 0;
  for (uint32_t i = 0; i < h->hdr->num_slots && n < max_entries; i++) {
    Slot* s = &h->slots[i];
    if (s->state == 2 && s->refcount == 0) {
      memcpy(out_ids + (uint64_t)n * kIdLen, s->id, kIdLen);
      out_sizes[n] = s->size;
      out_lru[n] = s->lru;
      n++;
    }
  }
  return (int)n;
}

// Abort an in-progress create (creator died or serialization failed).
int shm_abort(void* handle, const uint8_t* id) {
  Handle* h = static_cast<Handle*>(handle);
  MutexGuard g(&h->hdr->mutex);
  Slot* s = find_slot(h, id, false);
  if (!s || s->state != 1) return -ENOENT;
  heap_free(h, s->offset);
  s->state = 3;
  return 0;
}

}  // extern "C"
