"""Head-survival bench: the simulated-1000-node harness with the
acceptance pins applied.

Thin wrapper over ``ray_tpu._private.scale_sim`` (which does the real
work: registration storm, idle + contended control-RTT baselines, an
unthrottled overdrive flood that calibrates fold throughput and proves
the bounded queue sheds, a throttled 2x-overload leg where control-RPC
p99 must hold, a 32-node slice mass death whose fan-out must coalesce,
and a mid-load head SIGKILL with journal-replay + jittered-backoff
recovery). This wrapper runs it at full scale in a subprocess, applies
the pinned pass/fail criteria, and writes ``BENCH_head.json``.

Pins (FAIL lines + exit 1 on violation):

- overdrive overload_factor >= 2 with shed_total > 0 and the overload
  alert observed — the queue is genuinely bounded;
- 2x-overload control p99 within 5x baseline (idle or contended,
  whichever is kinder: on a single shared core the load generator's own
  CPU burn inflates every RTT, and the contended baseline exists to
  subtract exactly that) while still shedding;
- mass-death fan-out pushed frames << logical msgs x subscribers
  (coalesce ratio <= 0.25 — measured ~0.02);
- SIGKILL recovery: first RPC answered <= 15 s, every surviving node
  re-registered <= 60 s, journal records replayed, backoff jitter
  spread observed (> 50 ms across the reconnect storm).

Run: ``python bench_head.py [--nodes N] [--overload-s S]``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.abspath(__file__))


def run_sim(args) -> dict:
    out = os.path.join(tempfile.mkdtemp(prefix="bench-head-"),
                       "scale.json")
    cmd = [
        sys.executable, "-m", "ray_tpu._private.scale_sim",
        "--nodes", str(args.nodes),
        "--slice-nodes", str(args.slice_nodes),
        "--subscribers", str(args.subscribers),
        "--overload-s", str(args.overload_s),
        "--journal-keys", str(args.journal_keys),
        "--out", out,
    ]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(cmd, env=env, cwd=REPO)
    if proc.returncode != 0:
        raise SystemExit(f"scale_sim failed (exit {proc.returncode})")
    with open(out) as f:
        return json.load(f)


def apply_pins(doc: dict) -> list[str]:
    failures: list[str] = []

    def pin(ok: bool, msg: str):
        if not ok:
            failures.append(msg)

    ov = doc.get("overload", {})
    pin(ov.get("overload_factor", 0) >= 2.0,
        f"overdrive factor {ov.get('overload_factor')} < 2x")
    pin(ov.get("shed_total", 0) > 0, "overdrive leg never shed")
    pin(bool(ov.get("alert_seen")), "overload alert never fired")

    o2 = doc.get("overload_2x", {})
    vs = min(o2.get("p99_vs_idle", 1e9),
             o2.get("p99_vs_contended", 1e9))
    pin(vs <= 5.0,
        f"2x-overload control p99 {o2.get('control_p99_ms')}ms is "
        f"{vs}x baseline (> 5x)")
    pin(o2.get("shed_total", 0) > 0, "2x-overload leg never shed")
    pin(o2.get("overload_factor", 0) >= 1.5,
        f"2x leg realized factor {o2.get('overload_factor')} — "
        f"head was not meaningfully overloaded")

    md = doc.get("mass_death", {})
    ratio = md.get("coalesce_ratio", 1.0)
    pin(ratio <= 0.25,
        f"death fan-out coalesce ratio {ratio} > 0.25 "
        f"({md.get('pushed_frames')} frames for "
        f"{md.get('naive_frames')} naive)")

    rc = doc.get("sigkill_recovery", {})
    pin(rc.get("first_rpc_s", 1e9) <= 15.0,
        f"head answered first RPC {rc.get('first_rpc_s')}s after "
        f"restart (> 15s)")
    pin(rc.get("full_reconnect_s", 1e9) <= 60.0,
        f"full re-registration took {rc.get('full_reconnect_s')}s "
        f"(> 60s)")
    pin(rc.get("reconnected") == rc.get("expected"),
        f"only {rc.get('reconnected')}/{rc.get('expected')} nodes "
        f"re-registered")
    pin(rc.get("replayed_records", 0) > 0,
        "journal replayed zero records after SIGKILL")
    pin(rc.get("backoff_spread_s", 0) > 0.05,
        f"reconnect backoff spread {rc.get('backoff_spread_s')}s — "
        f"jitter not observed")

    pin(bool(doc.get("ok")), "harness reported not-ok")
    return failures


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1000)
    ap.add_argument("--slice-nodes", type=int, default=32)
    ap.add_argument("--subscribers", type=int, default=8)
    ap.add_argument("--overload-s", type=float, default=5.0)
    ap.add_argument("--journal-keys", type=int, default=2000)
    ap.add_argument("--output",
                    default=os.path.join(REPO, "BENCH_head.json"))
    args = ap.parse_args()

    doc = run_sim(args)
    failures = apply_pins(doc)
    doc["pins"] = {"failures": failures, "passed": not failures}

    with open(args.output, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc["pins"], indent=1))
    print(f"wrote {args.output}")
    for msg in failures:
        print(f"FAIL: {msg}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
