// Byte transport for the raytpu native protocol: a plain TCP socket,
// or TLS with the cluster's pinned self-signed certificate.
//
// TLS matches the Python client's posture (ray_tpu/_private/rpc.py
// _ssl_client_ctx): the cluster cert is the SOLE trust root
// (verify-peer against it; hostname irrelevant — any server holding
// the matching key is the cluster). The image ships OpenSSL 3 runtime
// libraries but no headers, so tls.cpp binds the needed functions from
// libssl.so.3 via dlopen against the stable C ABI — the same
// load-at-runtime approach the Python ssl module ultimately uses.
#pragma once

#include <memory>
#include <stdexcept>
#include <string>

namespace raytpu {

// Transport-level failure (peer unreachable / connection dropped):
// retryable by ReconnectingClient, unlike protocol errors.
class ConnectionError : public std::runtime_error {
 public:
  using std::runtime_error::runtime_error;
};

class Transport {
 public:
  virtual ~Transport() = default;
  // Full-buffer IO; throw ConnectionError on EOF/failure.
  virtual void WriteAll(const char* data, size_t n) = 0;
  virtual void ReadAll(char* data, size_t n) = 0;

  // cert_path empty = plaintext TCP. Throws ConnectionError when the
  // peer is unreachable, std::runtime_error for TLS setup/verification
  // failures (wrong cert = not retryable).
  static std::unique_ptr<Transport> Connect(const std::string& host,
                                            int port,
                                            const std::string& cert_path);

  // Server side over an accepted fd: with a cert+key, runs the TLS
  // handshake (the worker runtime's listener in a --tls cluster).
  static std::unique_ptr<Transport> Accept(int fd,
                                           const std::string& cert_path,
                                           const std::string& key_path);
};

}  // namespace raytpu
