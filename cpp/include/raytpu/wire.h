// Shared wire-framing primitives for the raytpu native protocol
// (ray_tpu/_private/rpc.py): little-endian u32 length header (the
// Python side's struct '<I'), serialized explicitly so big-endian
// hosts speak the same bytes. Used by both the client (client.cpp)
// and the worker runtime (worker.cpp) — one copy, so a framing fix
// can never desynchronize the two.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

namespace raytpu {
namespace wire {

constexpr uint8_t kWireVersion = 1;
constexpr int kReq = 0, kResp = 1, kErr = 2, kPush = 3;

inline void PutLe32(char* dst, uint32_t v) {
  dst[0] = static_cast<char>(v & 0xff);
  dst[1] = static_cast<char>((v >> 8) & 0xff);
  dst[2] = static_cast<char>((v >> 16) & 0xff);
  dst[3] = static_cast<char>((v >> 24) & 0xff);
}

inline uint32_t GetLe32(const char* src) {
  return static_cast<uint32_t>(static_cast<uint8_t>(src[0])) |
         (static_cast<uint32_t>(static_cast<uint8_t>(src[1])) << 8) |
         (static_cast<uint32_t>(static_cast<uint8_t>(src[2])) << 16) |
         (static_cast<uint32_t>(static_cast<uint8_t>(src[3])) << 24);
}

// All byte IO goes through Transport (transport.h) — raw-fd helpers
// were removed so nothing can silently bypass TLS.

}  // namespace wire
}  // namespace raytpu
