// C++-defined remote functions: registration + typed adapters.
//
// Reference: cpp/include/ray/api/ray_remote.h — the reference's
// RAY_REMOTE macro registers C++ functions at static-init time so a
// C++ worker can execute tasks submitted from any language. This is
// the TPU-native equivalent: functions register under a stable NAME,
// a Python (or C++) driver submits a task with fn_id "cfn:<name>" and
// msgpack args, and the raytpu worker runtime (worker.cpp) executes
// the registered function — arguments and results cross the language
// boundary as msgpack only, never pickle.
//
// Usage:
//   int64_t Add(int64_t a, int64_t b) { return a + b; }
//   RAYTPU_REMOTE(Add);
//   // Python: ray_tpu.cross_language.cpp_function("Add").remote(1, 2)
//
// Raw-Value functions (variadic / heterogeneous args) register too:
//   raytpu::Value Stats(const raytpu::ValueVec& args);
//   RAYTPU_REMOTE(Stats);
#pragma once

#include <functional>
#include <map>
#include <stdexcept>
#include <string>
#include <utility>

#include "raytpu/msgpack_lite.h"

namespace raytpu {

using TaskFn = std::function<Value(const ValueVec&)>;

// name -> function. A plain function-local static: initialization-order
// safe for the static registrars the macro expands to.
inline std::map<std::string, TaskFn>& FunctionRegistry() {
  static std::map<std::string, TaskFn> registry;
  return registry;
}

inline bool RegisterFunction(const std::string& name, TaskFn fn) {
  auto [it, inserted] = FunctionRegistry().emplace(name, std::move(fn));
  (void)it;
  if (!inserted)
    throw std::runtime_error("raytpu: duplicate RAYTPU_REMOTE name " + name);
  return true;
}

// ---- typed argument adapters (msgpack scalar types) -----------------
template <typename T>
T ValueTo(const Value& v);

template <>
inline int64_t ValueTo<int64_t>(const Value& v) {
  if (v.kind == Value::Kind::Int) return v.i;
  if (v.kind == Value::Kind::Float) return static_cast<int64_t>(v.f);
  throw std::runtime_error("raytpu: argument is not an integer");
}

template <>
inline double ValueTo<double>(const Value& v) {
  if (v.kind == Value::Kind::Float) return v.f;
  if (v.kind == Value::Kind::Int) return static_cast<double>(v.i);
  throw std::runtime_error("raytpu: argument is not a number");
}

template <>
inline std::string ValueTo<std::string>(const Value& v) {
  if (v.kind == Value::Kind::Str || v.kind == Value::Kind::Bin) return v.s;
  throw std::runtime_error("raytpu: argument is not a string");
}

template <>
inline bool ValueTo<bool>(const Value& v) {
  if (v.kind == Value::Kind::Bool) return v.b;
  throw std::runtime_error("raytpu: argument is not a bool");
}

inline Value ToValue(int64_t v) { return Value::I(v); }
inline Value ToValue(int v) { return Value::I(v); }
inline Value ToValue(double v) { return Value::F(v); }
inline Value ToValue(const std::string& v) { return Value::S(v); }
inline Value ToValue(bool v) { return Value::B(v); }
inline Value ToValue(Value v) { return v; }

namespace detail {

template <typename R, typename... Args, std::size_t... I>
TaskFn WrapTyped(R (*fn)(Args...), std::index_sequence<I...>) {
  return [fn](const ValueVec& args) -> Value {
    if (args.size() != sizeof...(Args))
      throw std::runtime_error(
          "raytpu: expected " + std::to_string(sizeof...(Args)) +
          " arguments, got " + std::to_string(args.size()));
    return ToValue(fn(ValueTo<std::decay_t<Args>>(args[I])...));
  };
}

// Raw form: Value fn(const ValueVec&) registers unwrapped.
inline TaskFn Wrap(Value (*fn)(const ValueVec&)) { return fn; }

template <typename R, typename... Args>
TaskFn Wrap(R (*fn)(Args...)) {
  return WrapTyped(fn, std::index_sequence_for<Args...>{});
}

}  // namespace detail
}  // namespace raytpu

// Static-init registration, like the reference's RAY_REMOTE.
#define RAYTPU_REMOTE(fn)                                        \
  static const bool _raytpu_registered_##fn =                    \
      ::raytpu::RegisterFunction(#fn, ::raytpu::detail::Wrap(fn))
