// Minimal msgpack codec for the ray_tpu control plane.
//
// Reference frame: the wire format is versioned msgpack
// (ray_tpu/_private/rpc.py pack_frame/unpack_frame; the reference's
// cross-language serialization is msgpack as well,
// python/ray/cross_language.py). This implements exactly the subset
// the control plane speaks: nil, bool, int, float64, str, bin,
// array, map<str|int, value>.
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace raytpu {

struct Value;
using ValueVec = std::vector<Value>;
using ValueMap = std::map<std::string, Value>;

struct Value {
  enum class Kind { Nil, Bool, Int, Float, Str, Bin, Array, Map };
  Kind kind = Kind::Nil;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;           // Str and Bin both live here
  std::shared_ptr<ValueVec> arr;
  std::shared_ptr<ValueMap> map;

  Value() = default;
  static Value Nil() { return Value(); }
  static Value B(bool v) { Value x; x.kind = Kind::Bool; x.b = v; return x; }
  static Value I(int64_t v) { Value x; x.kind = Kind::Int; x.i = v; return x; }
  static Value F(double v) { Value x; x.kind = Kind::Float; x.f = v; return x; }
  static Value S(std::string v) {
    Value x; x.kind = Kind::Str; x.s = std::move(v); return x;
  }
  static Value Bin(std::string v) {
    Value x; x.kind = Kind::Bin; x.s = std::move(v); return x;
  }
  static Value A(ValueVec v) {
    Value x; x.kind = Kind::Array;
    x.arr = std::make_shared<ValueVec>(std::move(v)); return x;
  }
  static Value M(ValueMap v) {
    Value x; x.kind = Kind::Map;
    x.map = std::make_shared<ValueMap>(std::move(v)); return x;
  }

  bool is_nil() const { return kind == Kind::Nil; }
  bool truthy() const {
    switch (kind) {
      case Kind::Nil: return false;
      case Kind::Bool: return b;
      case Kind::Int: return i != 0;
      case Kind::Float: return f != 0.0;
      case Kind::Str: case Kind::Bin: return !s.empty();
      case Kind::Array: return arr && !arr->empty();
      case Kind::Map: return map && !map->empty();
    }
    return false;
  }
  const Value& at(const std::string& key) const {
    static const Value kNil;
    if (kind != Kind::Map || !map) return kNil;
    auto it = map->find(key);
    return it == map->end() ? kNil : it->second;
  }
};

// ----------------------------------------------------------- encoding

inline void put_be(std::string& out, uint64_t v, int bytes) {
  for (int shift = (bytes - 1) * 8; shift >= 0; shift -= 8)
    out.push_back(static_cast<char>((v >> shift) & 0xff));
}

inline void encode(const Value& v, std::string& out) {
  using K = Value::Kind;
  switch (v.kind) {
    case K::Nil: out.push_back('\xc0'); break;
    case K::Bool: out.push_back(v.b ? '\xc3' : '\xc2'); break;
    case K::Int: {
      int64_t x = v.i;
      if (x >= 0 && x < 128) out.push_back(static_cast<char>(x));
      else if (x < 0 && x >= -32) out.push_back(static_cast<char>(x));
      else { out.push_back('\xd3'); put_be(out, static_cast<uint64_t>(x), 8); }
      break;
    }
    case K::Float: {
      out.push_back('\xcb');
      uint64_t bits; std::memcpy(&bits, &v.f, 8);
      put_be(out, bits, 8);
      break;
    }
    case K::Str: {
      size_t n = v.s.size();
      if (n < 32) out.push_back(static_cast<char>(0xa0 | n));
      else if (n < 256) { out.push_back('\xd9'); put_be(out, n, 1); }
      else { out.push_back('\xdb'); put_be(out, n, 4); }
      out.append(v.s);
      break;
    }
    case K::Bin: {
      size_t n = v.s.size();
      if (n < 256) { out.push_back('\xc4'); put_be(out, n, 1); }
      else { out.push_back('\xc6'); put_be(out, n, 4); }
      out.append(v.s);
      break;
    }
    case K::Array: {
      size_t n = v.arr ? v.arr->size() : 0;
      if (n < 16) out.push_back(static_cast<char>(0x90 | n));
      else { out.push_back('\xdd'); put_be(out, n, 4); }
      if (v.arr) for (const auto& e : *v.arr) encode(e, out);
      break;
    }
    case K::Map: {
      size_t n = v.map ? v.map->size() : 0;
      if (n < 16) out.push_back(static_cast<char>(0x80 | n));
      else { out.push_back('\xdf'); put_be(out, n, 4); }
      if (v.map)
        for (const auto& [k, e] : *v.map) { encode(Value::S(k), out); encode(e, out); }
      break;
    }
  }
}

inline std::string encode(const Value& v) {
  std::string out;
  encode(v, out);
  return out;
}

// ----------------------------------------------------------- decoding

struct Cursor {
  const uint8_t* p;
  size_t n;
  size_t off = 0;
  uint8_t u8() {
    if (off >= n) throw std::runtime_error("msgpack: truncated");
    return p[off++];
  }
  uint64_t be(int bytes) {
    uint64_t v = 0;
    for (int k = 0; k < bytes; ++k) v = (v << 8) | u8();
    return v;
  }
  std::string take(size_t len) {
    if (off + len > n) throw std::runtime_error("msgpack: truncated body");
    std::string out(reinterpret_cast<const char*>(p + off), len);
    off += len;
    return out;
  }
};

inline Value decode(Cursor& c);

inline Value decode_map(Cursor& c, size_t n) {
  ValueMap m;
  for (size_t k = 0; k < n; ++k) {
    Value key = decode(c);
    std::string ks;
    if (key.kind == Value::Kind::Str || key.kind == Value::Kind::Bin) ks = key.s;
    else if (key.kind == Value::Kind::Int) ks = std::to_string(key.i);
    else throw std::runtime_error("msgpack: unsupported map key kind");
    m.emplace(std::move(ks), decode(c));
  }
  return Value::M(std::move(m));
}

inline Value decode_arr(Cursor& c, size_t n) {
  ValueVec a;
  a.reserve(n);
  for (size_t k = 0; k < n; ++k) a.push_back(decode(c));
  return Value::A(std::move(a));
}

inline Value decode(Cursor& c) {
  uint8_t t = c.u8();
  if (t < 0x80) return Value::I(t);                       // pos fixint
  if (t >= 0xe0) return Value::I(static_cast<int8_t>(t)); // neg fixint
  if ((t & 0xf0) == 0x80) return decode_map(c, t & 0x0f); // fixmap
  if ((t & 0xf0) == 0x90) return decode_arr(c, t & 0x0f); // fixarray
  if ((t & 0xe0) == 0xa0) return Value::S(c.take(t & 0x1f)); // fixstr
  switch (t) {
    case 0xc0: return Value::Nil();
    case 0xc2: return Value::B(false);
    case 0xc3: return Value::B(true);
    case 0xc4: return Value::Bin(c.take(c.be(1)));
    case 0xc5: return Value::Bin(c.take(c.be(2)));
    case 0xc6: return Value::Bin(c.take(c.be(4)));
    case 0xca: {  // float32
      uint32_t bits = static_cast<uint32_t>(c.be(4));
      float fv; std::memcpy(&fv, &bits, 4);
      return Value::F(fv);
    }
    case 0xcb: {  // float64
      uint64_t bits = c.be(8);
      double fv; std::memcpy(&fv, &bits, 8);
      return Value::F(fv);
    }
    case 0xcc: return Value::I(static_cast<int64_t>(c.be(1)));
    case 0xcd: return Value::I(static_cast<int64_t>(c.be(2)));
    case 0xce: return Value::I(static_cast<int64_t>(c.be(4)));
    case 0xcf: return Value::I(static_cast<int64_t>(c.be(8)));
    case 0xd0: return Value::I(static_cast<int8_t>(c.be(1)));
    case 0xd1: return Value::I(static_cast<int16_t>(c.be(2)));
    case 0xd2: return Value::I(static_cast<int32_t>(c.be(4)));
    case 0xd3: return Value::I(static_cast<int64_t>(c.be(8)));
    case 0xd9: return Value::S(c.take(c.be(1)));
    case 0xda: return Value::S(c.take(c.be(2)));
    case 0xdb: return Value::S(c.take(c.be(4)));
    case 0xdc: return decode_arr(c, c.be(2));
    case 0xdd: return decode_arr(c, c.be(4));
    case 0xde: return decode_map(c, c.be(2));
    case 0xdf: return decode_map(c, c.be(4));
  }
  throw std::runtime_error("msgpack: unsupported type byte");
}

inline Value decode(const std::string& buf) {
  Cursor c{reinterpret_cast<const uint8_t*>(buf.data()), buf.size()};
  return decode(c);
}

}  // namespace raytpu
