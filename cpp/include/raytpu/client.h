// C++ driver client for a ray_tpu cluster.
//
// Reference: cpp/include/ray/api.h — the reference ships a C++ worker
// API (Init/Put/Get/Task(...).Remote()); this is the TPU-native
// driver-side equivalent over the runtime's native protocol: a
// blocking TCP client speaking the versioned-msgpack control plane
// (ray_tpu/_private/rpc.py framing), with cluster KV, node listing,
// and CROSS-LANGUAGE task calls — Python functions registered via
// ray_tpu._private.xlang.register_function, invoked by name with
// msgpack args, results returned as msgpack (pickle never crosses the
// boundary).
//
// Usage:
//   raytpu::Client head(host, port, token);
//   head.KvPut("greeting", "hello");
//   raytpu::Driver drv(head_addr, token);
//   raytpu::Value out = drv.Call("my_fn", {raytpu::Value::I(2)});
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "raytpu/msgpack_lite.h"

namespace raytpu {

// One rpc connection: REQ out, RESP/ERR in (PUSH frames are ignored —
// a blocking driver does not subscribe).
class Client {
 public:
  Client(const std::string& host, int port, const std::string& token);
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Call a control-plane method; kwargs is a msgpack map.
  Value Call(const std::string& method, ValueMap kwargs);

  // Block until the peer closes the connection, discarding any frames
  // (the worker runtime uses this to tie its lifetime to the node's).
  void WaitClosed();

  // -- convenience wrappers over head methods -----------------------
  void KvPut(const std::string& key, const std::string& value,
             bool overwrite = true);
  // Returns false when the key is absent.
  bool KvGet(const std::string& key, std::string* value_out);
  std::vector<std::string> KvKeys(const std::string& prefix);
  // node_id -> addr from the head's node table.
  ValueMap Nodes();

 private:
  void WriteFrame(const std::string& payload);
  std::string ReadFrame();
  int fd_ = -1;
  uint64_t next_id_ = 0;
};

// Cross-language task driver: lease a worker, push the task, return
// the lease (the same drive cycle core_worker._drive_normal_task runs).
class Driver {
 public:
  // head_addr "host:port". Connects to the head, discovers a node.
  Driver(const std::string& head_addr, const std::string& token);

  // Invoke a Python function registered as xfn:<name> with msgpack
  // args; returns its msgpack result. Throws std::runtime_error with
  // the remote error text on failure.
  Value Call(const std::string& name, ValueVec args, double num_cpus = 1.0);

  Client& head() { return head_; }

 private:
  std::string token_;
  Client head_;
  std::string node_host_;
  int node_port_ = 0;
};

}  // namespace raytpu
