// C++ driver client for a ray_tpu cluster.
//
// Reference: cpp/include/ray/api.h — the reference ships a C++ worker
// API (Init/Put/Get/Task(...).Remote()); this is the TPU-native
// driver-side equivalent over the runtime's native protocol: a
// blocking TCP client speaking the versioned-msgpack control plane
// (ray_tpu/_private/rpc.py framing), with cluster KV, node listing,
// and CROSS-LANGUAGE task calls — Python functions registered via
// ray_tpu._private.xlang.register_function, invoked by name with
// msgpack args, results returned as msgpack (pickle never crosses the
// boundary).
//
// Usage:
//   raytpu::Client head(host, port, token);
//   head.KvPut("greeting", "hello");
//   raytpu::Driver drv(head_addr, token);
//   raytpu::Value out = drv.Call("my_fn", {raytpu::Value::I(2)});
#pragma once

#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "raytpu/msgpack_lite.h"
#include "raytpu/transport.h"

namespace raytpu {

// One rpc connection: REQ out, RESP/ERR in (PUSH frames are ignored —
// a blocking driver does not subscribe). With a non-empty cert path
// the connection runs over TLS pinned to the cluster certificate
// (start --tls; matches the Python client's pinning posture).
class Client {
 public:
  Client(const std::string& host, int port, const std::string& token,
         const std::string& cert = "");
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Call a control-plane method; kwargs is a msgpack map.
  Value Call(const std::string& method, ValueMap kwargs);

  // Block until the peer closes the connection, discarding any frames
  // (the worker runtime uses this to tie its lifetime to the node's).
  void WaitClosed();

  // -- convenience wrappers over head methods -----------------------
  void KvPut(const std::string& key, const std::string& value,
             bool overwrite = true);
  // Returns false when the key is absent.
  bool KvGet(const std::string& key, std::string* value_out);
  std::vector<std::string> KvKeys(const std::string& prefix);
  // node_id -> addr from the head's node table.
  ValueMap Nodes();

 private:
  void WriteFrame(const std::string& payload);
  std::string ReadFrame();
  std::unique_ptr<Transport> transport_;
  uint64_t next_id_ = 0;
};

// Client endpoint that survives peer restarts: re-dials with backoff
// on connection loss and retries the in-flight call until a deadline
// (semantics of the Python ReconnectingClient, _private/rpc.py:500 —
// route IDEMPOTENT methods only; a call whose response was lost is
// re-sent).
class ReconnectingClient {
 public:
  ReconnectingClient(const std::string& host, int port,
                     const std::string& token,
                     const std::string& cert = "",
                     double reconnect_timeout_s = 20.0);

  // retry=false: non-idempotent call — a transport failure after the
  // request may have been sent surfaces instead of re-sending.
  Value Call(const std::string& method, ValueMap kwargs,
             bool retry = true);

 private:
  Client& Ensure();
  std::string host_;
  int port_;
  std::string token_;
  std::string cert_;
  double reconnect_timeout_s_;
  std::unique_ptr<Client> conn_;
};

// Cross-language task driver: lease a worker, push the task, return
// the lease (the same drive cycle core_worker._drive_normal_task runs).
class Driver {
 public:
  // head_addr "host:port". Connects to the head, discovers a node.
  Driver(const std::string& head_addr, const std::string& token,
         const std::string& cert = "");

  // Invoke a Python function registered as xfn:<name> with msgpack
  // args; returns its msgpack result. Throws std::runtime_error with
  // the remote error text on failure.
  Value Call(const std::string& name, ValueVec args, double num_cpus = 1.0);

  Client& head() { return head_; }

 private:
  std::string token_;
  std::string cert_;
  Client head_;
  std::string node_host_;
  int node_port_ = 0;
};

}  // namespace raytpu
