// Chaos probe: loop idempotent KV calls through ReconnectingClient
// while the test kills and restarts the head mid-stream. Prints
// "PROBE OK n=<iterations>" only if every call eventually succeeded —
// the C++ analogue of the Python ReconnectingClient chaos tests.
// Usage: raytpu_reconnect_probe <head_host:port> <iterations>
//        [token] [tls_cert]   (env fallbacks like the demo)
#include <csignal>
#include <cstdlib>
#include <iostream>

#include "raytpu/client.h"

int main(int argc, char** argv) {
  // TLS writes bypass MSG_NOSIGNAL: keep SIGPIPE from killing the
  // probe when the head dies mid-write.
  std::signal(SIGPIPE, SIG_IGN);
  if (argc < 3) {
    std::cerr << "usage: raytpu_reconnect_probe <head_host:port> "
                 "<iterations> [token] [tls_cert]\n";
    return 2;
  }
  std::string addr = argv[1];
  int iterations = std::atoi(argv[2]);
  std::string token = argc > 3 ? argv[3] : "";
  if (token.empty() && std::getenv("RAY_TPU_AUTH_TOKEN"))
    token = std::getenv("RAY_TPU_AUTH_TOKEN");
  std::string cert = argc > 4 ? argv[4] : "";
  if (cert.empty() && std::getenv("RAY_TPU_TLS_CERT"))
    cert = std::getenv("RAY_TPU_TLS_CERT");

  auto colon = addr.rfind(':');
  std::string host = addr.substr(0, colon);
  int port = std::stoi(addr.substr(colon + 1));
  raytpu::ReconnectingClient head(host, port, token, cert,
                                  /*reconnect_timeout_s=*/30.0);
  try {
    for (int i = 0; i < iterations; ++i) {
      raytpu::ValueMap put;
      put.emplace("key", raytpu::Value::S("cppprobe"));
      put.emplace("value",
                  raytpu::Value::Bin("i" + std::to_string(i)));
      put.emplace("overwrite", raytpu::Value::B(true));
      if (!head.Call("kv_put", std::move(put)).at("ok").truthy())
        throw std::runtime_error("kv_put rejected");
      raytpu::ValueMap get;
      get.emplace("key", raytpu::Value::S("cppprobe"));
      raytpu::Value reply = head.Call("kv_get", std::move(get));
      if (reply.at("value").s != "i" + std::to_string(i))
        throw std::runtime_error("kv_get mismatch at " +
                                 std::to_string(i));
      struct timespec ts {0, 100 * 1000000L};
      nanosleep(&ts, nullptr);
    }
  } catch (const std::exception& e) {
    std::cerr << "PROBE FAILED: " << e.what() << std::endl;
    return 1;
  }
  std::cout << "PROBE OK n=" << iterations << std::endl;
  return 0;
}
