// Demo/test raytpu C++ worker: a handful of RAYTPU_REMOTE functions
// plus the worker runtime entry point. Build: make -C cpp (produces
// build/raytpu_worker); the node manager spawns it when
// RAY_TPU_CPP_WORKER_CMD points here and a task's runtime_env is
// {"language": "cpp"}.
//
// Reference shape: cpp/src/ray/runtime/task/task_executor.cc executes
// RAY_REMOTE-registered functions; these examples mirror the
// reference's cpp/example functions in spirit.

#include <algorithm>
#include <stdexcept>
#include <string>

#include "raytpu/ray_remote.h"

namespace {

int64_t Add(int64_t a, int64_t b) { return a + b; }
RAYTPU_REMOTE(Add);

double Mul(double a, double b) { return a * b; }
RAYTPU_REMOTE(Mul);

std::string Greet(std::string name) { return "hello " + name; }
RAYTPU_REMOTE(Greet);

// Raw-Value form: heterogeneous args, structured return.
raytpu::Value SortInts(const raytpu::ValueVec& args) {
  if (args.empty() || args[0].kind != raytpu::Value::Kind::Array)
    throw std::runtime_error("SortInts expects one list argument");
  std::vector<int64_t> xs;
  for (const auto& v : *args[0].arr) xs.push_back(raytpu::ValueTo<int64_t>(v));
  std::sort(xs.begin(), xs.end());
  raytpu::ValueVec out;
  for (int64_t x : xs) out.push_back(raytpu::Value::I(x));
  raytpu::ValueMap m;
  m.emplace("sorted", raytpu::Value::A(std::move(out)));
  m.emplace("n", raytpu::Value::I(static_cast<int64_t>(xs.size())));
  return raytpu::Value::M(std::move(m));
}
RAYTPU_REMOTE(SortInts);

int64_t Boom(int64_t) {
  throw std::runtime_error("cpp kaboom");
}
RAYTPU_REMOTE(Boom);

}  // namespace

namespace raytpu {
int WorkerMain();
}

int main() { return raytpu::WorkerMain(); }
