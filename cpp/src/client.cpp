// See client.h. Wire framing per ray_tpu/_private/rpc.py:
//   [u32 le length][u8 wire-version=1][msgpack (kind, req_id, payload)]
// auth preamble: [u32 le length]["RTPUAUTH" + token]

#include "raytpu/client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <random>
#include <sstream>
#include <stdexcept>

#include "raytpu/wire.h"

namespace raytpu {

namespace {
using wire::kErr;
using wire::kPush;
using wire::kReq;
using wire::kResp;
using wire::kWireVersion;

std::string RandomHex(int bytes) {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  static const char* hex = "0123456789abcdef";
  std::string out;
  out.reserve(bytes * 2);
  for (int i = 0; i < bytes; ++i) {
    uint8_t b = static_cast<uint8_t>(rng());
    out.push_back(hex[b >> 4]);
    out.push_back(hex[b & 0xf]);
  }
  return out;
}

void SplitAddr(const std::string& addr, std::string* host, int* port) {
  if (!addr.empty() && addr[0] == '[') {
    // Bracketed IPv6 literal: "[::1]:8000".
    auto close = addr.find(']');
    if (close == std::string::npos || close + 1 >= addr.size() ||
        addr[close + 1] != ':')
      throw std::runtime_error("raytpu: address must be [v6host]:port");
    *host = addr.substr(1, close - 1);
    *port = std::stoi(addr.substr(close + 2));
    return;
  }
  // Unbracketed: split at the LAST colon. The port is always the final
  // component, so this is also correct for the unbracketed IPv6
  // literals the Python side announces (node/head format addresses as
  // f"{host}:{port}" with no brackets).
  auto pos = addr.rfind(':');
  if (pos == std::string::npos)
    throw std::runtime_error("raytpu: address must be host:port");
  *host = addr.substr(0, pos);
  *port = std::stoi(addr.substr(pos + 1));
}

using wire::GetLe32;
using wire::PutLe32;

void SleepMs(int ms) {
  struct timespec ts {
    ms / 1000, (ms % 1000) * 1000000L
  };
  nanosleep(&ts, nullptr);
}

double NowS() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}
}  // namespace

Client::Client(const std::string& host, int port, const std::string& token,
               const std::string& cert)
    : transport_(Transport::Connect(host, port, cert)) {
  if (!token.empty()) {
    std::string blob = "RTPUAUTH" + token;
    uint32_t len = static_cast<uint32_t>(blob.size());
    char hdr[4];
    PutLe32(hdr, len);
    transport_->WriteAll(hdr, 4);
    transport_->WriteAll(blob.data(), blob.size());
  }
}

Client::~Client() = default;

void Client::WriteFrame(const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size() + 1);
  char hdr[5];
  PutLe32(hdr, len);
  hdr[4] = static_cast<char>(kWireVersion);
  transport_->WriteAll(hdr, 5);
  transport_->WriteAll(payload.data(), payload.size());
}

std::string Client::ReadFrame() {
  char hdr[4];
  transport_->ReadAll(hdr, 4);
  uint32_t len = GetLe32(hdr);
  if (len == 0) throw std::runtime_error("raytpu: empty frame");
  std::string body(len, '\0');
  transport_->ReadAll(body.data(), len);
  if (static_cast<uint8_t>(body[0]) != kWireVersion)
    throw std::runtime_error("raytpu: wire version mismatch");
  return body.substr(1);
}

Value Client::Call(const std::string& method, ValueMap kwargs) {
  uint64_t req_id = ++next_id_;
  Value frame = Value::A({
      Value::I(kReq),
      Value::I(static_cast<int64_t>(req_id)),
      Value::A({Value::S(method), Value::M(std::move(kwargs))}),
  });
  WriteFrame(encode(frame));
  for (;;) {
    Value reply = decode(ReadFrame());
    if (reply.kind != Value::Kind::Array || reply.arr->size() != 3)
      throw std::runtime_error("raytpu: malformed reply frame");
    int64_t kind = (*reply.arr)[0].i;
    int64_t rid = (*reply.arr)[1].i;
    if (kind == kPush) continue;  // driver has no subscriptions
    if (rid != static_cast<int64_t>(req_id)) continue;  // stale
    if (kind == kErr)
      throw std::runtime_error("raytpu rpc error: " + (*reply.arr)[2].s);
    return (*reply.arr)[2];
  }
}

void Client::WaitClosed() {
  try {
    for (;;) (void)ReadFrame();
  } catch (const std::exception&) {
    // connection closed (or broke) — either way, the peer is gone.
  }
}

void Client::KvPut(const std::string& key, const std::string& value,
                   bool overwrite) {
  ValueMap kw;
  kw.emplace("key", Value::S(key));
  kw.emplace("value", Value::Bin(value));
  kw.emplace("overwrite", Value::B(overwrite));
  Value reply = Call("kv_put", std::move(kw));
  if (!reply.at("ok").truthy())
    throw std::runtime_error("raytpu: kv_put rejected for " + key);
}

bool Client::KvGet(const std::string& key, std::string* value_out) {
  ValueMap kw;
  kw.emplace("key", Value::S(key));
  Value reply = Call("kv_get", std::move(kw));
  if (!reply.at("ok").truthy()) return false;
  if (value_out) *value_out = reply.at("value").s;
  return true;
}

std::vector<std::string> Client::KvKeys(const std::string& prefix) {
  ValueMap kw;
  kw.emplace("prefix", Value::S(prefix));
  Value reply = Call("kv_keys", std::move(kw));
  std::vector<std::string> out;
  const Value& keys = reply.at("keys");
  if (keys.kind == Value::Kind::Array)
    for (const auto& k : *keys.arr) out.push_back(k.s);
  return out;
}

ValueMap Client::Nodes() {
  Value reply = Call("node_table", {});
  ValueMap out;
  if (reply.kind == Value::Kind::Map)
    for (const auto& [nid, info] : *reply.map)
      out.emplace(nid, info.at("addr"));
  return out;
}

Client& ReconnectingClient::Ensure() {
  if (!conn_)
    conn_ = std::make_unique<Client>(host_, port_, token_, cert_);
  return *conn_;
}

ReconnectingClient::ReconnectingClient(const std::string& host, int port,
                                       const std::string& token,
                                       const std::string& cert,
                                       double reconnect_timeout_s)
    : host_(host),
      port_(port),
      token_(token),
      cert_(cert),
      reconnect_timeout_s_(reconnect_timeout_s) {}

Value ReconnectingClient::Call(const std::string& method, ValueMap kwargs,
                               bool retry) {
  double deadline = NowS() + reconnect_timeout_s_;
  int backoff_ms = 200;
  for (;;) {
    // Dial and request are SEPARATE failure classes (the Python
    // client's err.sent distinction): a dial failure provably never
    // sent the request, so even retry=false calls re-dial; once the
    // request may have hit the wire, only idempotent (retry=true)
    // calls re-send — a lost RESPONSE must not double-execute a
    // non-idempotent method.
    bool dialed = false;
    try {
      Client& conn = Ensure();
      dialed = true;
      // kwargs are consumed by the encode; keep a copy for retries.
      ValueMap kw = kwargs;
      return conn.Call(method, std::move(kw));
    } catch (const ConnectionError& e) {
      conn_.reset();
      if (dialed && !retry) throw;
      if (NowS() >= deadline)
        throw ConnectionError(std::string("raytpu: peer did not come "
                                          "back within deadline: ") +
                              e.what());
      SleepMs(backoff_ms);
      backoff_ms = backoff_ms < 2000 ? backoff_ms * 2 : 2000;
    }
  }
}

Driver::Driver(const std::string& head_addr, const std::string& token,
               const std::string& cert)
    : token_(token),
      cert_(cert),
      head_([&] {
        std::string host;
        int port;
        SplitAddr(head_addr, &host, &port);
        return std::pair<std::string, int>(host, port);
      }()
                .first,
            [&] {
              std::string host;
              int port;
              SplitAddr(head_addr, &host, &port);
              return port;
            }(),
            token, cert) {
  // Probe the table: entries for recently-departed drivers linger
  // until the head's health sweep, so take the first node that
  // actually accepts a connection.
  ValueMap nodes = head_.Nodes();
  for (const auto& [nid, addr] : nodes) {
    (void)nid;
    std::string host;
    int port = 0;
    try {
      SplitAddr(addr.s, &host, &port);
      Client probe(host, port, token_, cert_);
      node_host_ = host;
      node_port_ = port;
      return;
    } catch (const std::exception&) {
      continue;
    }
  }
  throw std::runtime_error("raytpu: no reachable node in the cluster");
}

Value Driver::Call(const std::string& name, ValueVec args, double num_cpus) {
  Client node(node_host_, node_port_, token_, cert_);
  ValueMap resources;
  resources.emplace("CPU", Value::F(num_cpus));
  ValueMap lease_kw;
  lease_kw.emplace("resources", Value::M(std::move(resources)));
  lease_kw.emplace("actor", Value::B(false));
  Value lease = node.Call("lease_worker", std::move(lease_kw));
  if (!lease.at("ok").truthy())
    throw std::runtime_error("raytpu: lease failed: " + lease.at("error").s);
  std::string lease_id = lease.at("lease_id").s;
  std::string worker_addr = lease.at("addr").s;

  // Build the task spec: msgpack args, msgpack result (xlang=true).
  ValueVec encoded_args;
  for (auto& a : args) {
    ValueVec entry;
    entry.push_back(Value::Nil());  // positional slot
    entry.push_back(Value::S("mp"));
    entry.push_back(Value::Bin(encode(a)));
    encoded_args.push_back(Value::A(std::move(entry)));
  }
  ValueMap spec;
  spec.emplace("task_id", Value::S(RandomHex(16)));  // TaskID: 16 bytes
  spec.emplace("fn_id", Value::S("xfn:" + name));
  spec.emplace("args", Value::A(std::move(encoded_args)));
  spec.emplace("num_returns", Value::I(1));
  spec.emplace("name", Value::S(name));
  spec.emplace("xlang", Value::B(true));
  ValueMap push_kw;
  push_kw.emplace("spec", Value::M(std::move(spec)));

  std::string whost;
  int wport;
  SplitAddr(worker_addr, &whost, &wport);
  Value reply;
  try {
    Client worker(whost, wport, token_, cert_);
    reply = worker.Call("push_task", std::move(push_kw));
  } catch (...) {
    ValueMap ret;
    ret.emplace("lease_id", Value::S(lease_id));
    try { node.Call("return_lease", std::move(ret)); } catch (...) {}
    throw;
  }
  ValueMap ret;
  ret.emplace("lease_id", Value::S(lease_id));
  node.Call("return_lease", std::move(ret));

  if (reply.at("status").s != "ok") {
    std::string text = reply.at("error_text").s;
    throw std::runtime_error("raytpu task failed: " +
                             (text.empty() ? "(see worker log)" : text));
  }
  const Value& results = reply.at("results");
  if (results.kind != Value::Kind::Array || results.arr->empty())
    return Value::Nil();
  const Value& first = (*results.arr)[0];
  // (oid_hex, "xmp", msgpack-bytes)
  if (first.arr && first.arr->size() >= 3 && (*first.arr)[1].s == "xmp")
    return decode((*first.arr)[2].s);
  throw std::runtime_error("raytpu: unexpected result kind (not xlang?)");
}

}  // namespace raytpu
