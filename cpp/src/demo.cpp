// Demo/e2e driver: connect to a ray_tpu cluster from C++, exercise the
// cluster KV, node listing, and cross-language task calls.
// Usage: raytpu_demo <head_host:port> [token] [tls_cert]
// (token/cert also read from RAY_TPU_AUTH_TOKEN / RAY_TPU_TLS_CERT.)
#include <csignal>
#include <cstdlib>
#include <iostream>

#include "raytpu/client.h"

using raytpu::Client;
using raytpu::Driver;
using raytpu::Value;
using raytpu::ValueVec;

int main(int argc, char** argv) {
  std::signal(SIGPIPE, SIG_IGN);
  if (argc < 2) {
    std::cerr << "usage: raytpu_demo <head_host:port> [token] [tls_cert]\n";
    return 2;
  }
  std::string head_addr = argv[1];
  std::string token = argc > 2 ? argv[2] : "";
  if (token.empty() && std::getenv("RAY_TPU_AUTH_TOKEN"))
    token = std::getenv("RAY_TPU_AUTH_TOKEN");
  std::string cert = argc > 3 ? argv[3] : "";
  if (cert.empty() && std::getenv("RAY_TPU_TLS_CERT"))
    cert = std::getenv("RAY_TPU_TLS_CERT");

  try {
    Driver drv(head_addr, token, cert);

    // 1. Cluster KV round trip.
    drv.head().KvPut("cpp:hello", "from-cpp");
    std::string got;
    if (!drv.head().KvGet("cpp:hello", &got)) throw std::runtime_error("kv miss");
    std::cout << "KV " << got << "\n";

    // 2. Node discovery.
    std::cout << "NODES " << drv.head().Nodes().size() << "\n";

    // 3. Cross-language call: Python fn registered as xfn:cpp_add.
    Value sum = drv.Call("cpp_add", {Value::I(19), Value::I(23)});
    std::cout << "ADD " << sum.i << "\n";

    // 4. Structured args/result: list in, map out.
    ValueVec nums;
    for (int i = 1; i <= 4; ++i) nums.push_back(Value::I(i * i));
    Value stats = drv.Call("cpp_stats", {Value::A(std::move(nums))});
    std::cout << "STATS sum=" << stats.at("sum").i
              << " mean=" << stats.at("mean").f << "\n";

    // 5. Remote errors surface as text, not pickle.
    try {
      drv.Call("cpp_boom", {});
      std::cout << "ERROR missing\n";
      return 1;
    } catch (const std::exception& e) {
      std::cout << "RAISED " << e.what() << "\n";
    }
  } catch (const std::exception& e) {
    std::cerr << "FATAL " << e.what() << "\n";
    return 1;
  }
  std::cout << "CPP DRIVER OK\n";
  return 0;
}
