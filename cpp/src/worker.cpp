// raytpu C++ worker runtime: execute RAYTPU_REMOTE-registered
// functions as cluster tasks.
//
// Reference: cpp/src/ray/runtime/task/task_executor.cc — the
// reference's C++ worker receives leased tasks from the raylet and
// executes functions registered by RAY_REMOTE. TPU-native shape: the
// worker is an RPC SERVER speaking the runtime's versioned-msgpack
// wire (ray_tpu/_private/rpc.py framing). The node manager spawns this
// binary for leases whose runtime_env is {"language": "cpp"}
// (node.py _spawn_worker_cpp, config RAY_TPU_CPP_WORKER_CMD); it
// registers back like a Python worker and then serves push_task —
// drivers in ANY language connect to its advertised address and push
// specs whose fn_id is "cfn:<name>".
//
// Protocol surface served: push_task, ping, exit_worker. Execution is
// serialized (a worker is leased to one driver at a time; the mutex
// guards against overlapped pushes). Errors travel as
// {"status": "error", "error_text": ...} — the Python owner raises a
// RayTaskError from the text (pickle never crosses the boundary).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <csignal>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "raytpu/client.h"
#include "raytpu/msgpack_lite.h"
#include "raytpu/ray_remote.h"
#include "raytpu/transport.h"
#include "raytpu/wire.h"

namespace raytpu {
namespace {

using wire::kReq;
using wire::kResp;
using wire::kWireVersion;

bool WriteFrame(Transport& t, const std::string& payload) {
  char hdr[5];
  wire::PutLe32(hdr, static_cast<uint32_t>(payload.size() + 1));
  hdr[4] = static_cast<char>(kWireVersion);
  try {
    t.WriteAll(hdr, 5);
    t.WriteAll(payload.data(), payload.size());
    return true;
  } catch (const ConnectionError&) {
    return false;
  }
}

// Reads one framed blob WITHOUT interpreting the version byte — the
// auth preamble has none, frames do.
bool ReadBlob(Transport& t, std::string* out, uint32_t max_len = 1u << 30) {
  char hdr[4];
  try {
    t.ReadAll(hdr, 4);
    uint32_t len = wire::GetLe32(hdr);
    if (len == 0 || len > max_len) return false;
    out->resize(len);
    t.ReadAll(out->data(), len);
    return true;
  } catch (const ConnectionError&) {
    return false;
  }
}

std::mutex g_exec_mutex;

Value ExecutePushTask(const Value& spec) {
  const Value& fn_id = spec.at("fn_id");
  std::string name = fn_id.s;
  if (name.rfind("cfn:", 0) == 0) name = name.substr(4);
  auto it = FunctionRegistry().find(name);
  if (it == FunctionRegistry().end())
    throw std::runtime_error("cpp function '" + name +
                             "' is not registered in this worker");
  ValueVec args;
  const Value& arg_entries = spec.at("args");
  if (arg_entries.kind == Value::Kind::Array) {
    for (const auto& entry : *arg_entries.arr) {
      // (slot, "mp", msgpack-bytes): cross-language args only.
      if (!entry.arr || entry.arr->size() < 3 || (*entry.arr)[1].s != "mp")
        throw std::runtime_error(
            "cpp worker accepts msgpack ('mp') arguments only");
      args.push_back(decode((*entry.arr)[2].s));
    }
  }
  Value result;
  {
    std::lock_guard<std::mutex> lock(g_exec_mutex);
    result = it->second(args);
  }
  // Result oid mirrors ids.py ObjectID.for_return(task_id, 0):
  // task binary + 4-byte big-endian index (hex: 8 zero chars).
  std::string oid_hex = spec.at("task_id").s + "00000000";
  ValueVec triple;
  triple.push_back(Value::S(oid_hex));
  triple.push_back(Value::S("xmp"));
  triple.push_back(Value::Bin(encode(result)));
  ValueVec results;
  results.push_back(Value::A(std::move(triple)));
  ValueMap reply;
  reply.emplace("status", Value::S("ok"));
  reply.emplace("results", Value::A(std::move(results)));
  return Value::M(std::move(reply));
}

void ServeConn(int fd, const std::string& token,
               const std::string& cert, const std::string& key) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  std::unique_ptr<Transport> transport;
  try {
    // Accept owns the fd: it closes exactly once on failure.
    transport = Transport::Accept(fd, cert, key);
  } catch (const std::exception& e) {
    // A TLS misconfiguration (bad key, missing cert) silently eating
    // every connection is undebuggable: say why each accept died.
    std::cerr << "raytpu_worker: connection rejected: " << e.what()
              << std::endl;
    return;
  }
  Transport& t = *transport;
  std::string blob;
  if (!token.empty()) {
    // First blob must be the auth preamble; constant-time-ish compare
    // is unnecessary here (the token has full entropy and this worker
    // binds like the Python workers do).
    if (!ReadBlob(t, &blob, 4096) || blob != "RTPUAUTH" + token)
      return;  // transport dtor closes the fd
  }
  for (;;) {
    if (!ReadBlob(t, &blob)) break;
    if (static_cast<uint8_t>(blob[0]) != kWireVersion) break;
    Value frame;
    int64_t req_id = 0;
    try {
      frame = decode(blob.substr(1));
      if (frame.kind != Value::Kind::Array || frame.arr->size() != 3 ||
          (*frame.arr)[0].i != kReq)
        break;
      req_id = (*frame.arr)[1].i;
      const Value& payload = (*frame.arr)[2];
      // The worker binds a real port: validate the payload shape
      // before dereferencing (a malformed frame must fail the request,
      // not segfault the process and every in-flight task with it).
      if (payload.kind != Value::Kind::Array || !payload.arr ||
          payload.arr->size() < 2)
        throw std::runtime_error("cpp worker: malformed request payload");
      const std::string& method = (*payload.arr)[0].s;
      const Value& kwargs = (*payload.arr)[1];
      Value result;
      if (method == "push_task") {
        result = ExecutePushTask(kwargs.at("spec"));
      } else if (method == "ping") {
        ValueMap ok;
        ok.emplace("ok", Value::B(true));
        result = Value::M(std::move(ok));
      } else if (method == "exit_worker") {
        ValueMap ok;
        ok.emplace("ok", Value::B(true));
        ValueVec resp;
        resp.push_back(Value::I(kResp));
        resp.push_back(Value::I(req_id));
        resp.push_back(Value::M(std::move(ok)));
        WriteFrame(t, encode(Value::A(std::move(resp))));
        std::exit(0);
      } else {
        throw std::runtime_error("cpp worker: unknown method " + method);
      }
      ValueVec resp;
      resp.push_back(Value::I(kResp));
      resp.push_back(Value::I(req_id));
      resp.push_back(std::move(result));
      if (!WriteFrame(t, encode(Value::A(std::move(resp))))) break;
    } catch (const std::exception& e) {
      // Task-level failures travel as status=error replies (the owner
      // raises RayTaskError); only protocol-level breakage uses kErr.
      ValueMap reply;
      reply.emplace("status", Value::S("error"));
      reply.emplace("error_text", Value::S(e.what()));
      ValueVec resp;
      resp.push_back(Value::I(kResp));
      resp.push_back(Value::I(req_id));
      resp.push_back(Value::M(std::move(reply)));
      if (!WriteFrame(t, encode(Value::A(std::move(resp))))) break;
    }
  }
}

std::string EnvOr(const char* key, const std::string& fallback) {
  const char* v = std::getenv(key);
  return v ? std::string(v) : fallback;
}

}  // namespace

int WorkerMain() {
  ::signal(SIGPIPE, SIG_IGN);
  std::string node_addr = EnvOr("RAY_TPU_NODE_ADDR", "");
  std::string worker_id = EnvOr("RAY_TPU_WORKER_ID", "");
  std::string token = EnvOr("RAY_TPU_AUTH_TOKEN", "");
  // In a --tls cluster the node exports the cluster cert/key; the
  // worker then dials out TLS-pinned AND serves TLS itself.
  std::string cert = EnvOr("RAY_TPU_TLS_CERT", "");
  std::string key = EnvOr("RAY_TPU_TLS_KEY", "");
  if (node_addr.empty() || worker_id.empty()) {
    std::cerr << "raytpu_worker: RAY_TPU_NODE_ADDR and RAY_TPU_WORKER_ID "
                 "must be set (this binary is spawned by the node manager)"
              << std::endl;
    return 2;
  }
  auto colon = node_addr.rfind(':');
  std::string node_host = node_addr.substr(0, colon);
  int node_port = std::stoi(node_addr.substr(colon + 1));

  // Listening endpoint: same interface family/host the node uses.
  int listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd < 0) return 2;
  int one = 1;
  ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = 0;
  if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
          0 ||
      ::listen(listen_fd, 16) != 0) {
    std::cerr << "raytpu_worker: cannot bind" << std::endl;
    return 2;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  int port = ntohs(addr.sin_port);
  std::string my_addr = node_host + ":" + std::to_string(port);

  // Register with the node over a persistent connection; its closure
  // means the node died -> exit (same contract as worker_main.py).
  auto* node = new Client(node_host, node_port, token, cert);
  ValueMap kw;
  kw.emplace("worker_id", Value::S(worker_id));
  kw.emplace("addr", Value::S(my_addr));
  kw.emplace("pid", Value::I(static_cast<int64_t>(::getpid())));
  Value reply = node->Call("register_worker", std::move(kw));
  if (!reply.at("ok").truthy()) {
    std::cerr << "raytpu_worker: registration rejected" << std::endl;
    return 2;
  }
  std::cerr << "raytpu_worker " << worker_id.substr(0, 8) << " serving "
            << my_addr << " (" << FunctionRegistry().size()
            << " registered fns)" << std::endl;
  std::thread([node] {
    // Blocking read on the node connection: EOF = node gone.
    node->WaitClosed();
    std::exit(0);
  }).detach();

  for (;;) {
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    std::thread(ServeConn, fd, token, cert, key).detach();
  }
}

}  // namespace raytpu
