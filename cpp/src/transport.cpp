// See transport.h. TLS binds libssl.so.3 / libcrypto.so.3 at runtime
// (no OpenSSL headers in the image); only stable OpenSSL 3 C-ABI
// entry points are used.

#include "raytpu/transport.h"

#include <dlfcn.h>
#include <netdb.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <mutex>

namespace raytpu {
namespace {

int DialTcp(const std::string& host, int port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res)
    throw ConnectionError("raytpu: cannot resolve " + host);
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) ::close(fd);
    throw ConnectionError("raytpu: cannot connect to " + host + ":" +
                          port_s);
  }
  freeaddrinfo(res);
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

class PlainTransport : public Transport {
 public:
  explicit PlainTransport(int fd) : fd_(fd) {}
  ~PlainTransport() override {
    if (fd_ >= 0) ::close(fd_);
  }
  void WriteAll(const char* data, size_t n) override {
    while (n > 0) {
      // MSG_NOSIGNAL: a peer that vanished mid-write must surface as
      // ConnectionError (ReconnectingClient's retry signal), not
      // SIGPIPE-kill the process.
      ssize_t w = ::send(fd_, data, n, MSG_NOSIGNAL);
      if (w <= 0) throw ConnectionError("raytpu: connection write failed");
      data += w;
      n -= static_cast<size_t>(w);
    }
  }
  void ReadAll(char* data, size_t n) override {
    while (n > 0) {
      ssize_t r = ::read(fd_, data, n);
      if (r <= 0) throw ConnectionError("raytpu: connection closed");
      data += r;
      n -= static_cast<size_t>(r);
    }
  }

 private:
  int fd_;
};

// ---- OpenSSL 3 ABI, bound at runtime ---------------------------------
struct SslApi {
  // Opaque handles; the ABI passes pointers only.
  using SSL_CTX = void;
  using SSL = void;
  using SSL_METHOD = void;

  const SSL_METHOD* (*TLS_client_method)();
  const SSL_METHOD* (*TLS_server_method)();
  int (*SSL_CTX_use_certificate_chain_file)(SSL_CTX*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(SSL_CTX*, const char*, int);
  int (*SSL_accept)(SSL*);
  SSL_CTX* (*SSL_CTX_new)(const SSL_METHOD*);
  void (*SSL_CTX_free)(SSL_CTX*);
  int (*SSL_CTX_load_verify_locations)(SSL_CTX*, const char*, const char*);
  void (*SSL_CTX_set_verify)(SSL_CTX*, int, void*);
  SSL* (*SSL_new)(SSL_CTX*);
  void (*SSL_free)(SSL*);
  int (*SSL_set_fd)(SSL*, int);
  int (*SSL_connect)(SSL*);
  int (*SSL_read)(SSL*, void*, int);
  int (*SSL_write)(SSL*, const void*, int);
  int (*SSL_shutdown)(SSL*);
  long (*SSL_get_verify_result)(const SSL*);

  static const SslApi& Get() {
    static SslApi api = Load();
    return api;
  }

 private:
  static SslApi Load() {
    void* ssl = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!ssl) ssl = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (!ssl)
      throw std::runtime_error(
          "raytpu: TLS requested but libssl.so.3 is not loadable");
    SslApi api{};
    auto bind = [&](const char* name) -> void* {
      void* fn = dlsym(ssl, name);
      if (!fn)
        throw std::runtime_error(
            std::string("raytpu: libssl is missing ") + name);
      return fn;
    };
    api.TLS_client_method = reinterpret_cast<const SSL_METHOD* (*)()>(
        bind("TLS_client_method"));
    api.TLS_server_method = reinterpret_cast<const SSL_METHOD* (*)()>(
        bind("TLS_server_method"));
    api.SSL_CTX_use_certificate_chain_file =
        reinterpret_cast<int (*)(SSL_CTX*, const char*)>(
            bind("SSL_CTX_use_certificate_chain_file"));
    api.SSL_CTX_use_PrivateKey_file =
        reinterpret_cast<int (*)(SSL_CTX*, const char*, int)>(
            bind("SSL_CTX_use_PrivateKey_file"));
    api.SSL_accept =
        reinterpret_cast<int (*)(SSL*)>(bind("SSL_accept"));
    api.SSL_CTX_new = reinterpret_cast<SSL_CTX* (*)(const SSL_METHOD*)>(
        bind("SSL_CTX_new"));
    api.SSL_CTX_free =
        reinterpret_cast<void (*)(SSL_CTX*)>(bind("SSL_CTX_free"));
    api.SSL_CTX_load_verify_locations =
        reinterpret_cast<int (*)(SSL_CTX*, const char*, const char*)>(
            bind("SSL_CTX_load_verify_locations"));
    api.SSL_CTX_set_verify =
        reinterpret_cast<void (*)(SSL_CTX*, int, void*)>(
            bind("SSL_CTX_set_verify"));
    api.SSL_new = reinterpret_cast<SSL* (*)(SSL_CTX*)>(bind("SSL_new"));
    api.SSL_free = reinterpret_cast<void (*)(SSL*)>(bind("SSL_free"));
    api.SSL_set_fd =
        reinterpret_cast<int (*)(SSL*, int)>(bind("SSL_set_fd"));
    api.SSL_connect =
        reinterpret_cast<int (*)(SSL*)>(bind("SSL_connect"));
    api.SSL_read =
        reinterpret_cast<int (*)(SSL*, void*, int)>(bind("SSL_read"));
    api.SSL_write = reinterpret_cast<int (*)(SSL*, const void*, int)>(
        bind("SSL_write"));
    api.SSL_shutdown =
        reinterpret_cast<int (*)(SSL*)>(bind("SSL_shutdown"));
    api.SSL_get_verify_result =
        reinterpret_cast<long (*)(const SSL*)>(
            bind("SSL_get_verify_result"));
    return api;
  }
};

constexpr int kVerifyPeer = 0x01;  // SSL_VERIFY_PEER
constexpr long kX509VOk = 0;       // X509_V_OK

// Shared TLS plumbing: fd/ctx/ssl ownership, IO loops, teardown. The
// two subclasses differ only in handshake direction and trust setup.
// fd ownership: TlsBase ADOPTS the fd at construction (which cannot
// fail), so even when a derived constructor throws, ~TlsBase runs and
// closes the fd exactly once — the factories never close it, which is
// what prevents double-close races against concurrently accepted fds
// reusing the number.
class TlsBase : public Transport {
 public:
  ~TlsBase() override {
    if (ssl_) SslApi::Get().SSL_shutdown(ssl_);
    FreeSsl();
    if (fd_ >= 0) ::close(fd_);
  }

  void WriteAll(const char* data, size_t n) override {
    const SslApi& api = SslApi::Get();
    while (n > 0) {
      int w = api.SSL_write(ssl_, data, static_cast<int>(n));
      if (w <= 0) throw ConnectionError("raytpu: TLS write failed");
      data += w;
      n -= static_cast<size_t>(w);
    }
  }

  void ReadAll(char* data, size_t n) override {
    const SslApi& api = SslApi::Get();
    while (n > 0) {
      int r = api.SSL_read(ssl_, data, static_cast<int>(n));
      if (r <= 0) throw ConnectionError("raytpu: TLS connection closed");
      data += r;
      n -= static_cast<size_t>(r);
    }
  }

 protected:
  explicit TlsBase(int fd) : fd_(fd) {}

  void FreeSsl() {
    const SslApi& api = SslApi::Get();
    if (ssl_) api.SSL_free(ssl_);
    if (ctx_) api.SSL_CTX_free(ctx_);
    ssl_ = nullptr;
    ctx_ = nullptr;
  }

  // Allocate ssl_ on ctx_ and bind the fd; throws (leaving the fd to
  // the factory) instead of letting SSL_accept/connect crash on null.
  void NewSslOrThrow() {
    const SslApi& api = SslApi::Get();
    ssl_ = api.SSL_new(ctx_);
    if (!ssl_) {
      FreeSsl();
      throw ConnectionError("raytpu: SSL_new failed");
    }
    api.SSL_set_fd(ssl_, fd_);
  }

  int fd_;
  SslApi::SSL_CTX* ctx_ = nullptr;
  SslApi::SSL* ssl_ = nullptr;
};

class TlsTransport : public TlsBase {
 public:
  TlsTransport(int fd, const std::string& cert_path) : TlsBase(fd) {
    const SslApi& api = SslApi::Get();
    ctx_ = api.SSL_CTX_new(api.TLS_client_method());
    if (!ctx_) {
      FreeSsl();
      throw std::runtime_error("raytpu: SSL_CTX_new failed");
    }
    // Pin: the cluster cert is the only trust root.
    if (api.SSL_CTX_load_verify_locations(ctx_, cert_path.c_str(),
                                          nullptr) != 1) {
      FreeSsl();
      throw std::runtime_error("raytpu: cannot load TLS cert " +
                               cert_path);
    }
    api.SSL_CTX_set_verify(ctx_, kVerifyPeer, nullptr);
    NewSslOrThrow();
    if (api.SSL_connect(ssl_) != 1) {
      // With SSL_VERIFY_PEER, a pinning mismatch fails INSIDE the
      // handshake: read the verify result before cleanup so the
      // caller gets a non-retryable error (ReconnectingClient must
      // not spin its whole deadline against a wrong/rotated cert).
      long verify = api.SSL_get_verify_result(ssl_);
      FreeSsl();
      if (verify != kX509VOk)
        throw std::runtime_error(
            "raytpu: server certificate does not match the pinned "
            "cluster cert (verify result " + std::to_string(verify) +
            ")");
      throw ConnectionError("raytpu: TLS handshake failed");
    }
    if (api.SSL_get_verify_result(ssl_) != kX509VOk) {
      FreeSsl();
      throw std::runtime_error(
          "raytpu: server certificate does not match the pinned "
          "cluster cert");
    }
  }
};

// Server side over an ACCEPTED fd (the worker runtime's listener in a
// --tls cluster; cert/key are the cluster's own material, the same
// files the Python servers load).
class TlsServerTransport : public TlsBase {
 public:
  TlsServerTransport(int fd, const std::string& cert_path,
                     const std::string& key_path)
      : TlsBase(fd) {
    constexpr int kFiletypePem = 1;  // SSL_FILETYPE_PEM
    const SslApi& api = SslApi::Get();
    ctx_ = api.SSL_CTX_new(api.TLS_server_method());
    if (!ctx_) {
      FreeSsl();
      throw std::runtime_error("raytpu: SSL_CTX_new (server) failed");
    }
    if (api.SSL_CTX_use_certificate_chain_file(
            ctx_, cert_path.c_str()) != 1 ||
        api.SSL_CTX_use_PrivateKey_file(ctx_, key_path.c_str(),
                                        kFiletypePem) != 1) {
      FreeSsl();
      throw std::runtime_error(
          "raytpu: cannot load TLS cert/key for serving");
    }
    NewSslOrThrow();
    if (api.SSL_accept(ssl_) != 1) {
      FreeSsl();
      throw ConnectionError("raytpu: TLS accept failed");
    }
  }
};

}  // namespace

std::unique_ptr<Transport> Transport::Connect(
    const std::string& host, int port, const std::string& cert_path) {
  int fd = DialTcp(host, port);
  if (cert_path.empty())
    return std::make_unique<PlainTransport>(fd);
  // TlsBase adopted the fd the moment construction began; on a
  // handshake throw its destructor already closed it.
  return std::make_unique<TlsTransport>(fd, cert_path);
}

std::unique_ptr<Transport> Transport::Accept(
    int fd, const std::string& cert_path, const std::string& key_path) {
  if (cert_path.empty())
    return std::make_unique<PlainTransport>(fd);
  return std::make_unique<TlsServerTransport>(fd, cert_path, key_path);
}

}  // namespace raytpu
