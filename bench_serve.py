"""Serve load-generator bench: TTFT/latency percentiles + tokens/s.

The pinned-baseline stub for the production-serve tentpole (ROADMAP:
"Land a load-generator bench (`bench_serve.py`) reporting p50/p99 TTFT
+ tokens/s"). It drives real HTTP traffic through the proxy against

- an **echo** deployment (the request-path floor: proxy + router +
  replica round trip), and
- a **tiny-model LLM** deployment with an SSE token stream (the
  continuous-batching path: prefill/decode through the engine),

measures client-side TTFT/latency percentiles, and cross-checks them
against the head's serve SLO ledger (`serve_stats` — the same numbers
`ray_tpu slo` and /api/serve show), so the bench and the telemetry can
never drift apart silently. Emits ``BENCH_serve.json``:

- ``echo``: requests, p50/p99 latency ms, requests/s
- ``llm_stream``: requests, p50/p99 TTFT ms, p50/p99 latency ms,
  generated tokens/s
- ``serve_stats``: the head ledger rows for both deployments
  (attainment, window percentiles, alert state)

The serve tentpole PR (KV-aware routing, prefill/decode disaggregation,
SLO autoscaling) pins its regressions against this format. A replica-
kill leg (p50/p99 under a mid-bench kill) lands with that PR — the
drain path it needs is already in place.

Run: ``python bench_serve.py [--requests N] [--concurrency C]``
(writes BENCH_serve.json next to this file).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import socket
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(values, q):
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return ordered[idx]


def _unary(port, path, body, timeout=60):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()
    return time.perf_counter() - t0


def _sse(port, path, body, timeout=120):
    """One streamed request; returns (ttft_s, latency_s, n_tokens)."""
    payload = json.dumps(body).encode()
    req = (
        f"POST {path} HTTP/1.1\r\nHost: 127.0.0.1\r\n"
        f"Accept: text/event-stream\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    t0 = time.perf_counter()
    ttft = None
    tokens = 0
    raw = b""
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.sendall(req)
        while b"data: [DONE]" not in raw and b"event: error" not in raw:
            chunk = s.recv(65536)
            if not chunk:
                break
            if ttft is None and b"data: " in raw + chunk:
                ttft = time.perf_counter() - t0
            raw += chunk
    latency = time.perf_counter() - t0
    for ln in raw.decode("utf-8", "replace").splitlines():
        if ln.startswith("data: ") and ln != "data: [DONE]":
            try:
                tokens += len(json.loads(ln[len("data: "):])["tokens"])
            except (ValueError, KeyError, TypeError):
                pass
    return ttft if ttft is not None else latency, latency, tokens


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=24)
    ap.add_argument("--output", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_serve.json"))
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serve_integration import build_llm_deployment
    from ray_tpu.util import state

    ray_tpu.init(num_cpus=max(8, args.concurrency))

    @serve.deployment(max_ongoing_requests=64)
    def echo(request):
        return {"ok": True, "n": request["body"].get("n", 0)}

    serve.run(echo.bind(), name="bench_echo", route_prefix="/echo")
    llm = build_llm_deployment(
        "tiny",
        engine_kwargs={"max_batch": 8},
        ray_actor_options={"num_cpus": 0.5},
    )
    serve.run(llm, name="bench_llm", route_prefix="/llm", timeout_s=180)
    port = serve.start_http()

    # Warmup (route tables, first compile).
    _unary(port, "/echo", {"n": -1})
    _sse(port, "/llm", {"prompt": "warm", "max_tokens": 4, "stream": True})

    # ---- echo leg: unary request-path floor under concurrency
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        echo_lat = list(pool.map(
            lambda i: _unary(port, "/echo", {"n": i}),
            range(args.requests),
        ))
    echo_wall = time.perf_counter() - t0

    # ---- llm leg: SSE token streaming through the batcher
    n_llm = max(8, args.requests // 4)
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        llm_rows = list(pool.map(
            lambda i: _sse(
                port, "/llm",
                {"prompt": f"bench {i}", "max_tokens": args.max_tokens,
                 "stream": True},
            ),
            range(n_llm),
        ))
    llm_wall = time.perf_counter() - t0
    ttfts = [r[0] for r in llm_rows]
    lats = [r[1] for r in llm_rows]
    toks = sum(r[2] for r in llm_rows)

    # Give the 1 Hz span flush a beat, then read the head ledger — the
    # cross-check that keeps client-side and telemetry numbers honest.
    deadline = time.time() + 10
    ledger = {}
    while time.time() < deadline:
        ledger = state.serve_stats().get("deployments", {})
        got = ledger.get("bench_llm/LLMServer", {}).get("requests", 0)
        if got >= n_llm:
            break
        time.sleep(0.5)

    out = {
        "bench": "serve",
        "requests": args.requests,
        "concurrency": args.concurrency,
        "echo": {
            "requests": args.requests,
            "latency_p50_ms": round(_percentile(echo_lat, 0.5) * 1e3, 2),
            "latency_p99_ms": round(_percentile(echo_lat, 0.99) * 1e3, 2),
            "requests_per_s": round(args.requests / echo_wall, 1),
        },
        "llm_stream": {
            "requests": n_llm,
            "max_tokens": args.max_tokens,
            "ttft_p50_ms": round(_percentile(ttfts, 0.5) * 1e3, 2),
            "ttft_p99_ms": round(_percentile(ttfts, 0.99) * 1e3, 2),
            "latency_p50_ms": round(_percentile(lats, 0.5) * 1e3, 2),
            "latency_p99_ms": round(_percentile(lats, 0.99) * 1e3, 2),
            "tokens_per_s": round(toks / llm_wall, 1),
        },
        "serve_stats": {
            k: v for k, v in ledger.items()
            if k.startswith(("bench_echo/", "bench_llm/"))
        },
    }
    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(f"wrote {args.output}")

    serve.shutdown()
    ray_tpu.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
