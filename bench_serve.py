"""Serve load-generator bench: TTFT/latency percentiles + tokens/s,
plus the control-plane legs (replica kill, drain scale-down, autoscale
cycle).

The pinned baseline for the production-serve tentpole. It drives real
HTTP traffic through the proxy against

- an **echo** deployment (the request-path floor: proxy + router +
  replica round trip),
- a **tiny-model LLM** deployment (2 replicas) with an SSE token stream
  (the continuous-batching path: prefill/decode through the engine),

measures client-side TTFT/latency percentiles, cross-checks them
against the head's serve SLO ledger (`serve_stats` — the same numbers
`ray_tpu slo` and /api/serve show), and then exercises the serve
control plane end to end:

- ``scale_down_drain``: serve.scale 2→1 mid-load — the drain protocol
  must finish every in-flight stream and re-route the rest
  (**dropped must be 0**);
- ``replica_kill``: SIGKILL one of two replicas mid-load — bounded p99
  TTFT degradation, typed failures only (**hung must be 0**), recovery
  back to two replicas;
- ``autoscale_cycle``: an autoscaled deployment under
  high → idle → high load — target replicas must track the load with
  no flapping (direction changes ≤ 3 over the whole cycle).

Emits ``BENCH_serve.json``. Run:
``python bench_serve.py [--requests N] [--concurrency C]``.
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import socket
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentile(values, q):
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * (len(ordered) - 1) + 0.5))
    return ordered[idx]


def _ms(v):
    return round(v * 1e3, 2) if v is not None else None


def _unary(port, path, body, timeout=60):
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        resp.read()
    return time.perf_counter() - t0


def _sse(port, path, body, timeout=120):
    """One streamed request; returns (status, ttft_s, latency_s,
    n_tokens) with status ∈ ok | error | hung. "hung" means the client
    timed out waiting — the exact failure mode the typed control plane
    exists to remove; "error" is a typed, client-visible failure (SSE
    error frame, non-200, or dropped connection)."""
    payload = json.dumps(body).encode()
    req = (
        f"POST {path} HTTP/1.1\r\nHost: 127.0.0.1\r\n"
        f"Accept: text/event-stream\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    t0 = time.perf_counter()
    ttft = None
    raw = b""
    status = "error"
    try:
        with socket.create_connection(
            ("127.0.0.1", port), timeout=timeout
        ) as s:
            s.sendall(req)
            while True:
                if b"data: [DONE]" in raw:
                    status = "ok"
                    break
                if b"event: error" in raw or b" 503 " in raw[:64] \
                        or b" 500 " in raw[:64]:
                    status = "error"
                    break
                chunk = s.recv(65536)
                if not chunk:
                    status = "error"  # connection dropped mid-stream
                    break
                if ttft is None and b"data: " in raw + chunk:
                    ttft = time.perf_counter() - t0
                raw += chunk
    except socket.timeout:
        status = "hung"
    except OSError:
        status = "error"
    latency = time.perf_counter() - t0
    tokens = 0
    for ln in raw.decode("utf-8", "replace").splitlines():
        if ln.startswith("data: ") and ln != "data: [DONE]":
            try:
                tokens += len(json.loads(ln[len("data: "):])["tokens"])
            except (ValueError, KeyError, TypeError):
                pass
    return status, (ttft if ttft is not None else latency), latency, tokens


def _stream_load(port, n, concurrency, max_tokens, mid_hook=None,
                 hook_at=None):
    """Drive n SSE requests at the given concurrency; optionally fire
    ``mid_hook()`` once, right after the ``hook_at``-th request is
    ISSUED. Returns the list of (status, ttft, latency, tokens) rows
    and the wall time."""
    rows = [None] * n
    issued = 0
    lock = threading.Lock()
    fired = threading.Event()

    def one(i):
        nonlocal issued
        with lock:
            issued += 1
            fire = (
                mid_hook is not None
                and hook_at is not None
                and issued == hook_at
                and not fired.is_set()
            )
        if fire:
            fired.set()
            mid_hook()
        rows[i] = _sse(
            port, "/llm",
            {"prompt": f"bench {i}", "max_tokens": max_tokens,
             "stream": True},
            timeout=60,
        )

    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(concurrency) as pool:
        list(pool.map(one, range(n)))
    return rows, time.perf_counter() - t0


def _stream_leg_summary(rows, wall):
    oks = [r for r in rows if r[0] == "ok"]
    ttfts = [r[1] for r in oks]
    lats = [r[2] for r in oks]
    return {
        "requests": len(rows),
        "ok": len(oks),
        "errors": sum(1 for r in rows if r[0] == "error"),
        "hung": sum(1 for r in rows if r[0] == "hung"),
        "ttft_p50_ms": _ms(_percentile(ttfts, 0.5)),
        "ttft_p99_ms": _ms(_percentile(ttfts, 0.99)),
        "latency_p50_ms": _ms(_percentile(lats, 0.5)),
        "latency_p99_ms": _ms(_percentile(lats, 0.99)),
        "tokens_per_s": round(sum(r[3] for r in oks) / wall, 1),
    }


def _wait_replicas(serve, app, dep, want, timeout_s=60):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        st = serve.status()[app][dep]
        if st["replicas"] == want and st["draining"] == 0:
            return time.monotonic() - t0
        time.sleep(0.25)
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=24)
    ap.add_argument("--output", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_serve.json"))
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu._private import config as rconfig
    from ray_tpu._private.test_utils import kill_one_replica
    from ray_tpu.llm.serve_integration import build_llm_deployment
    from ray_tpu.util import state

    # Short down-cooldown so the autoscale leg's full down-up cycle fits
    # a bench run (exported to env BEFORE the controller spawns).
    rconfig.set_system_config({"SERVE_AUTOSCALE_DOWN_COOLDOWN_S": 2.0})
    ray_tpu.init(num_cpus=max(8, args.concurrency))

    @serve.deployment(max_ongoing_requests=64)
    def echo(request):
        return {"ok": True, "n": request["body"].get("n", 0)}

    serve.run(echo.bind(), name="bench_echo", route_prefix="/echo")
    llm = build_llm_deployment(
        "tiny",
        num_replicas=2,
        engine_kwargs={"max_batch": 8},
        ray_actor_options={"num_cpus": 0.5},
    )
    serve.run(llm, name="bench_llm", route_prefix="/llm", timeout_s=180)
    port = serve.start_http()

    # Warmup (route tables, first compile — both replicas).
    _unary(port, "/echo", {"n": -1})
    for i in range(4):
        _sse(port, "/llm",
             {"prompt": f"warm {i}", "max_tokens": 4, "stream": True})

    # ---- echo leg: unary request-path floor under concurrency
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        echo_lat = list(pool.map(
            lambda i: _unary(port, "/echo", {"n": i}),
            range(args.requests),
        ))
    echo_wall = time.perf_counter() - t0

    # ---- llm leg: SSE token streaming through the batcher (baseline)
    n_llm = max(8, args.requests // 4)
    base_rows, base_wall = _stream_load(
        port, n_llm, args.concurrency, args.max_tokens
    )
    base = _stream_leg_summary(base_rows, base_wall)

    # ---- scale-down drain leg: 2 → 1 mid-load, ZERO drops required
    drain_rows, drain_wall = _stream_load(
        port, n_llm, args.concurrency, args.max_tokens,
        mid_hook=lambda: serve.scale("LLMServer", 1,
                                     app_name="bench_llm"),
        hook_at=max(2, n_llm // 4),
    )
    drain = _stream_leg_summary(drain_rows, drain_wall)
    drain["dropped"] = drain["errors"] + drain["hung"]
    _wait_replicas(serve, "bench_llm", "LLMServer", 1, 60)
    serve.scale("LLMServer", 2, app_name="bench_llm")
    recovery = _wait_replicas(serve, "bench_llm", "LLMServer", 2, 120)
    drain["scaled_back_up_s"] = round(recovery, 2) if recovery else None
    # Re-warm the fresh replica's compile outside the kill leg's clock.
    for i in range(4):
        _sse(port, "/llm",
             {"prompt": f"rewarm {i}", "max_tokens": 4, "stream": True})

    # ---- replica-kill leg: SIGKILL 1 of 2 mid-load
    kill_rows, kill_wall = _stream_load(
        port, n_llm, args.concurrency, args.max_tokens,
        mid_hook=lambda: kill_one_replica("LLMServer", "bench_llm"),
        hook_at=max(2, n_llm // 4),
    )
    kill = _stream_leg_summary(kill_rows, kill_wall)
    recovery = _wait_replicas(serve, "bench_llm", "LLMServer", 2, 120)
    kill["recovered_replicas"] = serve.status()["bench_llm"][
        "LLMServer"]["replicas"]
    kill["recovery_s"] = (
        round(recovery, 2) if recovery is not None else None
    )
    kill["ttft_p99_degradation_x"] = (
        round(kill["ttft_p99_ms"] / base["ttft_p99_ms"], 2)
        if kill.get("ttft_p99_ms") and base.get("ttft_p99_ms")
        else None
    )

    # ---- autoscale leg: high → idle → high, target must track load
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3,
            target_ongoing_requests=2, downscale_delay_s=2.0,
        ),
    )
    def busy(x):
        time.sleep(0.15)
        return x

    serve.run(busy.bind(), name="bench_auto")
    handle = serve.get_app_handle("bench_auto")
    handle.remote(0).result(timeout=60)

    targets: list[int] = []
    sampling = threading.Event()

    def sample_targets():
        while not sampling.is_set():
            targets.append(
                serve.status()["bench_auto"]["busy"]["target"]
            )
            time.sleep(0.2)

    sampler = threading.Thread(target=sample_targets, daemon=True)
    sampler.start()

    def burst(seconds):
        stop = time.monotonic() + seconds
        while time.monotonic() < stop:
            futs = [handle.remote(i) for i in range(8)]
            for f in futs:
                f.result(timeout=60)

    burst(6.0)          # high load → scale up
    time.sleep(6.0)     # idle → sustained-low scale down
    burst(5.0)          # high again → scale back up
    time.sleep(1.0)
    sampling.set()
    sampler.join(timeout=5)

    changes = [
        (a, b) for a, b in zip(targets, targets[1:]) if a != b
    ]
    direction_changes = 0
    last_dir = 0
    for a, b in zip(targets, targets[1:]):
        d = (b > a) - (b < a)
        if d and d != last_dir:
            direction_changes += 1
            last_dir = d
    autoscale = {
        "targets": targets,
        "peak_target": max(targets) if targets else None,
        "trough_target": min(targets) if targets else None,
        "transitions": changes,
        "direction_changes": direction_changes,
        "flapping": direction_changes > 3,
        "tracked_load": bool(
            targets
            and max(targets) >= 2
            and min(targets[len(targets) // 3:]) == 1
            and max(targets[2 * len(targets) // 3:]) >= 2
        ),
    }

    # Give the 1 Hz span flush a beat, then read the head ledger — the
    # cross-check that keeps client-side and telemetry numbers honest.
    deadline = time.time() + 10
    ledger = {}
    while time.time() < deadline:
        ledger = state.serve_stats().get("deployments", {})
        got = ledger.get("bench_llm/LLMServer", {}).get("requests", 0)
        if got >= 3 * n_llm:
            break
        time.sleep(0.5)

    out = {
        "bench": "serve",
        "requests": args.requests,
        "concurrency": args.concurrency,
        "echo": {
            "requests": args.requests,
            "latency_p50_ms": _ms(_percentile(echo_lat, 0.5)),
            "latency_p99_ms": _ms(_percentile(echo_lat, 0.99)),
            "requests_per_s": round(args.requests / echo_wall, 1),
        },
        "llm_stream": {"max_tokens": args.max_tokens, **base},
        "scale_down_drain": drain,
        "replica_kill": kill,
        "autoscale_cycle": autoscale,
        "serve_stats": {
            k: v for k, v in ledger.items()
            if k.startswith(
                ("bench_echo/", "bench_llm/", "bench_auto/")
            )
        },
    }
    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    print(f"wrote {args.output}")

    failures = []
    if drain["dropped"] != 0:
        failures.append(
            f"scale_down_drain dropped {drain['dropped']} requests"
        )
    if kill["hung"] != 0:
        failures.append(f"replica_kill hung {kill['hung']} requests")
    if kill["recovered_replicas"] != 2:
        failures.append("replica_kill did not recover to 2 replicas")
    if autoscale["flapping"]:
        failures.append("autoscale target flapped")
    if not autoscale["tracked_load"]:
        failures.append("autoscale target did not track load")
    for f in failures:
        print(f"FAIL: {f}")

    serve.shutdown()
    ray_tpu.shutdown()
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
