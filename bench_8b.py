"""8B-scale single-chip memory validation (BASELINE.md north star
de-risk): run REAL Llama-3-8B layers — full d_model 4096 / d_ff 14336 /
32q+8kv heads at head_dim 128 — with the exact remat + flash +
chunked-CE recipe the pod run would use, sized to one v5e chip the way
ZeRO-3 shards it.

On a v5p-64 FSDP pod each chip holds 1/64 of params+opt state
(~16 B/param · 8B / 64 ≈ 2 GB) plus its batch shard's activations. One
v5e chip can't hold 8B params, so this bench keeps N full-size layers
plus a PER-CHIP VOCAB SHARD of the embedding/head (8k of 128k rows — the
full fp32-adamw table is ~15 GB and never sits on one chip even on the
pod) and runs real train steps at seq 4096. Passing proves the
activation/remat memory recipe for full-size layers; the full-vocab
table is only ever exercised sharded, exactly as deployed.

Prints ONE JSON line (separate from bench.py's headline metric).
"""

from __future__ import annotations

import dataclasses
import json
import time


def run(n_layers: int, batch: int, seq: int, steps: int = 5) -> dict:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import PRESETS
    from ray_tpu.parallel import make_mesh
    from ray_tpu.train.step import (
        init_train_state,
        jit_train_step,
        make_optimizer,
    )

    cfg = dataclasses.replace(
        PRESETS["llama3_8b"],
        n_layers=n_layers,
        # The 128k-vocab embedding/head is ZeRO-sharded on the pod
        # (~8k rows per chip on a 16-chip slice); model the per-chip
        # shard, not the full table — full-vocab fp32 adamw alone is
        # ~15 GB and can never sit on one chip.
        vocab_size=8192,
        attn_impl="flash",
        remat="full",
    )
    opt = make_optimizer(total_steps=1000, mu_dtype=jnp.bfloat16)
    mesh = make_mesh({"dp": 1})
    step = jit_train_step(cfg, opt, mesh)
    state = init_train_state(jax.random.key(0), cfg, opt)
    tokens = jax.random.randint(
        jax.random.key(1), (batch, seq + 1), 0, cfg.vocab_size
    )
    batch_d = {"tokens": tokens}
    for _ in range(2):
        state, metrics = step(state, batch_d)
        float(state.params["final_norm"][0])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_d)
    float(state.params["final_norm"][0])
    loss = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / steps
    per_layer_ms = dt / n_layers * 1e3
    # Peak HBM through the memory signal plane (runtime/memory.py):
    # backend memory_stats where exposed, live-array byte accounting
    # where it isn't (the axon case) — the fallback reports the
    # resident state between steps (params + optimizer + batch), the
    # floor of the true in-step peak.
    from ray_tpu.runtime import memory as rmem

    samp = rmem.sample(emit=False) or {}
    hbm = samp.get("hbm") or {}
    peak = hbm.get("peak_bytes") or hbm.get("used_bytes")
    hbm_gb = round(peak / 2**30, 2) if peak else None
    return {
        "metric": "llama3_8b_layer_memory_validation",
        "n_full_layers": n_layers,
        "params": cfg.num_params(),
        "batch": batch,
        "seq": seq,
        "step_time_s": round(dt, 3),
        "per_layer_ms": round(per_layer_ms, 1),
        "tokens_per_sec": round(batch * seq / dt, 1),
        "loss": round(loss, 3),
        "peak_hbm_gb": hbm_gb,
        "peak_hbm_source": hbm.get("source"),
        "hbm_by_kind_gb": {
            k: round(v / 2**30, 2)
            for k, v in (hbm.get("by_kind") or {}).items()
            if v
        },
        "ok": True,
    }


def planner_block(
    committed: "tuple[int, int]", oom_at: "list[list[int]]"
) -> dict:
    """Predicted-vs-empirical fit verdicts for every attempted config:
    the analytic planner (ray_tpu.train.memory.plan) priced against
    the same 16 GB v5e the empirical boundary was measured on. A
    mismatch on any config means the byte model drifted from reality
    and fails tier-1 (tests/test_memory_plane.py pins this block)."""
    from ray_tpu.train.memory import plan_bench8b

    configs = []
    all_match = True
    for n_layers, batch in [tuple(c) for c in oom_at] + [committed]:
        p = plan_bench8b(n_layers, batch)
        empirical = "oom" if [n_layers, batch] in oom_at else "fits"
        predicted = "fits" if p.fits else "oom"
        match = predicted == empirical
        all_match = all_match and match
        configs.append({
            "config": [n_layers, batch],
            "predicted_gb": round(p.total_gb, 2),
            "predicted_headroom_gb": round(
                p.headroom_bytes / 2**30, 2
            ),
            "predicted": predicted,
            "empirical": empirical,
            "match": match,
        })
    return {
        "model": "analytic (ray_tpu.train.memory.plan): fp32 params + "
                 "adamw(bf16 mu) + fp32 grads + remat-full activations "
                 "+ chunked-CE logits vs 16 GiB minus XLA reserve",
        "hbm_gb": 16.0,
        "reserve_gb": 0.5,
        "configs": configs,
        "all_match": all_match,
    }


def main() -> None:
    import os
    import subprocess
    import sys

    one = os.environ.get("BENCH8B_CONFIG")
    if one:
        n_layers, batch = (int(x) for x in one.split(","))
        try:
            print(json.dumps(run(n_layers=n_layers, batch=batch, seq=4096)))
        except Exception as e:  # noqa: BLE001 - parent reads rc/stderr
            print(
                json.dumps(
                    {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
                )
            )
            sys.exit(1)
        return

    # Full-size 8B layers; start at the LARGEST candidate and back off
    # on OOM — the first success is the committed max-that-fits. Each
    # attempt runs in a FRESH process: a TPU ResourceExhausted leaves
    # the backend unreliable for later in-process attempts.
    last_err = "no config attempted"
    oom_at = []
    for n_layers, batch in (
        (12, 1), (10, 1), (8, 2), (8, 1), (6, 2), (6, 1),
        (4, 2), (4, 1), (2, 1), (1, 1),
    ):
        env = dict(os.environ, BENCH8B_CONFIG=f"{n_layers},{batch}")
        try:
            proc = subprocess.run(
                [sys.executable, __file__],
                capture_output=True,
                text=True,
                env=env,
                timeout=560,
            )
        except subprocess.TimeoutExpired:
            # A too-big config can wedge in compile/swap; treat like an
            # OOM and keep backing off (the contract is ONE JSON line).
            oom_at.append([n_layers, batch])
            last_err = f"timeout at layers={n_layers} batch={batch}"
            continue
        lines = [
            ln for ln in proc.stdout.splitlines() if ln.startswith("{")
        ]
        if proc.returncode == 0 and lines:
            rec = json.loads(lines[-1])
            # The OOM'd larger configs ARE the headroom measurement
            # when the backend exposes no memory_stats: the fit
            # boundary sits between the committed config and these —
            # and the analytic planner must agree with every verdict.
            rec["oom_at"] = oom_at
            rec["planner"] = planner_block((n_layers, batch), oom_at)
            print(json.dumps(rec))
            return
        oom_at.append([n_layers, batch])
        last_err = (lines[-1] if lines else proc.stderr[-300:]) or "?"
    print(
        json.dumps(
            {
                "metric": "llama3_8b_layer_memory_validation",
                "ok": False,
                "error": last_err,
            }
        )
    )


if __name__ == "__main__":
    main()
