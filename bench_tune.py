"""Sweep-engine bench: gang packing, preemption migration, PBT forks.

Three legs against a fake-chip cluster (CPU-backed workers), with the
acceptance pins applied and ``BENCH_tune.json`` written:

- **Packing**: an 8-trial sweep on 4 fake chips. Gang admission packs
  trials onto idle chips concurrently — pinned: makespan < 0.6x the
  naive sequential sum of trial durations, and time-weighted
  chip_idle_fraction < 0.25.
- **Kill**: a trial's node is drained (preemption notice) and killed
  mid-sweep; the gang takes the emergency checkpoint at the next step
  boundary and re-admits elsewhere — pinned: <= 1 step re-run per
  kill, and the sweep journals the migration.
- **Fork**: a PBT exploit forks the winner's checkpoint manifest into
  the loser's run through the content-addressed store — pinned: the
  head reports new_bytes == 0 and the dedup assertion measures 0 new
  chunks (ratio 1.0).

Run: ``python bench_tune.py [--trials N] [--steps N]``.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.abspath(__file__))


# ---------------------------------------------------------- trial loops
def _packing_loop(config):
    import time as _t

    from ray_tpu import train

    for step in range(config["steps"]):
        _t.sleep(config["step_s"])
        train.report({"loss": float(config["lr"]) / (step + 1)})


def _kill_loop(config):
    import json as _json
    import os as _os
    import time as _t

    from ray_tpu import train

    ctx = train.get_context()
    start = 0
    ck = train.get_checkpoint()
    if ck:
        with open(_os.path.join(ck, "state.json")) as f:
            start = _json.load(f)["step"] + 1
    scratch = config["scratch"]
    with open(
        _os.path.join(scratch, f"start_attempt{ctx.attempt}"), "w"
    ) as f:
        f.write(str(start))
    if ctx.attempt == 0 and ctx.rank == 0:
        from ray_tpu import api as _api

        with open(config["marker"], "w") as f:
            f.write(_api._runtime.core.node_addr or "")
    for step in range(start, config["steps"]):
        _t.sleep(0.15)
        with open(
            _os.path.join(scratch, f"prog_attempt{ctx.attempt}"), "w"
        ) as f:
            f.write(str(step))
        ckdir = None
        if step % 4 == 0 or train.preemption_notice() is not None:
            ckdir = _os.path.join(scratch, f"ck_{step}")
            _os.makedirs(ckdir, exist_ok=True)
            with open(_os.path.join(ckdir, "state.json"), "w") as f:
                _json.dump({"step": step}, f)
        train.report({"loss": 1.0 / (step + 1)}, checkpoint=ckdir)


def _fork_loop(config):
    import time as _t

    import numpy as np

    from ray_tpu import checkpoint as ckpt
    from ray_tpu import train

    start = 0
    state = {"w": np.ones(1024, np.float32) * config["lr"]}
    uri = train.get_checkpoint()
    if uri and ckpt.is_ckpt_uri(uri):
        state = ckpt.restore_uri(uri, target=state)
        start = ckpt.parse_uri(uri)[1] + 1
    cp = ckpt.AsyncCheckpointer()
    for step in range(start, config["steps"]):
        _t.sleep(0.1)
        cp.save(step, state)
        train.report({"loss": float(config["lr"])})
    cp.wait()


# ----------------------------------------------------------------- legs
def leg_packing(trials: int, steps: int, chips: int) -> dict:
    import ray_tpu
    from ray_tpu import tune

    os.environ["RAY_TPU_FAKE_CHIPS"] = str(chips)
    ray_tpu.init(num_cpus=max(8, chips * 2))
    try:
        sweep = tune.Sweep(
            _packing_loop,
            {
                "lr": tune.grid_search(
                    [round(0.1 * (i + 1), 2) for i in range(trials)]
                ),
                "steps": steps,
                "step_s": 0.1,
            },
            sweep_id="bench-pack",
            config=tune.SweepConfig(
                num_samples=1, workers_per_trial=1,
                chips_per_worker=1.0, poll_s=0.1,
            ),
        )
        res = sweep.run()
        durations = [
            t.ended_ts - t.started_ts
            for t in sweep.trials
            if t.started_ts and t.ended_ts
        ]
        naive = sum(durations)
        makespan = res.stats["makespan_s"]
        return {
            "trials": len(res.trials),
            "chips": chips,
            "all_terminated": all(
                t.state == "TERMINATED" for t in res.trials
            ),
            "makespan_s": round(makespan, 3),
            "naive_sequential_s": round(naive, 3),
            "speedup": round(naive / makespan, 2) if makespan else None,
            "makespan_over_naive": (
                round(makespan / naive, 3) if naive else None
            ),
            "chip_idle_fraction": (
                round(res.stats["chip_idle_fraction"], 4)
                if res.stats["chip_idle_fraction"] is not None
                else None
            ),
        }
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_FAKE_CHIPS", None)


def leg_kill(tmp: str, steps: int = 14) -> dict:
    import ray_tpu
    from ray_tpu import api as core_api
    from ray_tpu import tune
    from ray_tpu.runtime.node import NodeManager
    from ray_tpu.util import state as util_state

    ray_tpu.init(num_cpus=2, _system_config={"HEALTH_TIMEOUT_S": 4.0})
    rt = core_api._runtime
    nodes = []

    async def launch(i):
        node = NodeManager(
            rt.core.head_addr,
            os.path.join(tmp, f"slice{i}_store"),
            resources={"CPU": 2.0, "SLICE": 1.0},
        )
        await node.start()
        return node

    for i in range(2):
        nodes.append(rt.run(launch(i)))
    try:
        marker = os.path.join(tmp, "victim_addr")
        scratch = os.path.join(tmp, "scratch")
        os.makedirs(scratch, exist_ok=True)
        sweep = tune.Sweep(
            _kill_loop,
            {"steps": steps, "scratch": scratch, "marker": marker},
            sweep_id="bench-kill",
            storage_path=os.path.join(tmp, "results"),
            config=tune.SweepConfig(
                num_samples=1, workers_per_trial=1,
                resources_per_worker={"SLICE": 1.0},
                poll_s=0.1, max_failures=3,
            ),
        )

        def drainer():
            deadline = time.monotonic() + 60
            while (
                time.monotonic() < deadline
                and not os.path.exists(marker)
            ):
                time.sleep(0.05)
            with open(marker) as f:
                victim_addr = f.read().strip()
            victim = next(n for n in nodes if n.addr == victim_addr)

            async def drain():
                return await rt.core.head.call(
                    "drain_node", node_id=victim.node_id,
                    reason="preemption-notice", deadline_s=4.0,
                )

            rt.run(drain())
            time.sleep(4.0)
            for w in list(victim.workers.values()):
                proc = w.get("proc")
                if proc and proc.poll() is None:
                    proc.kill()
            try:
                rt.run(victim.stop())
            # tpulint: allow(broad-except reason=bench teardown; the node may already be dead from the kill leg)
            except Exception:
                pass

        th = threading.Thread(target=drainer, daemon=True)
        th.start()
        res = sweep.run()
        th.join(timeout=30)

        trial = res.trials[0]
        with open(os.path.join(scratch, "prog_attempt0")) as f:
            last_before_kill = int(f.read())
        with open(os.path.join(scratch, "start_attempt1")) as f:
            resumed_at = int(f.read())
        rec = util_state.sweep_stats()["sweeps"]["bench-kill"]
        return {
            "steps": steps,
            "trial_state": trial.state,
            "attempts": trial.attempts,
            "journaled_preemptions": rec["preemptions"],
            "last_step_before_kill": last_before_kill,
            "resumed_at_step": resumed_at,
            "steps_lost_per_kill": last_before_kill - resumed_at + 1,
        }
    finally:
        for node in nodes:
            try:
                rt.run(node.stop())
            # tpulint: allow(broad-except reason=bench teardown; the node may already be dead from the kill leg)
            except Exception:
                pass
        ray_tpu.shutdown()
        from ray_tpu._private import config as _config

        _config._overrides.pop("HEALTH_TIMEOUT_S", None)
        os.environ.pop("RAY_TPU_HEALTH_TIMEOUT_S", None)


def leg_fork(steps: int = 12) -> dict:
    import ray_tpu
    from ray_tpu import checkpoint as ckpt
    from ray_tpu import tune
    from ray_tpu.util import state as util_state

    os.environ["RAY_TPU_FAKE_CHIPS"] = "3"
    ray_tpu.init(num_cpus=8)
    try:
        sweep = tune.Sweep(
            _fork_loop,
            {"lr": tune.grid_search([0.1, 0.5, 0.9]), "steps": steps},
            sweep_id="bench-fork",
            config=tune.SweepConfig(
                num_samples=1, workers_per_trial=1,
                chips_per_worker=1.0,
                pbt=tune.LedgerPBT(
                    metric="loss", mode="min",
                    perturbation_interval=4,
                    hyperparam_mutations={"lr": [0.05]},
                    quantile_fraction=0.34, seed=7,
                ),
                poll_s=0.15,
            ),
        )
        res = sweep.run()
        forked = [t for t in res.trials if t.forked_from]
        out = {"forks": res.stats["forks"], "fork_recs": []}
        for t in forked:
            rec = util_state.sweep_stats()["sweeps"]["bench-fork"][
                "trials"
            ][t.trial_id]
            share = ckpt.fork_shares_chunks(
                f"bench-fork/{t.forked_from}",
                f"bench-fork/{t.trial_id}",
                rec["fork_step"],
            )
            out["fork_recs"].append(
                {
                    "loser": t.trial_id,
                    "winner": t.forked_from,
                    "fork_step": rec["fork_step"],
                    **share,
                }
            )
        return out
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_FAKE_CHIPS", None)


# ----------------------------------------------------------------- pins
def apply_pins(doc: dict) -> list[str]:
    failures: list[str] = []

    def pin(ok: bool, msg: str) -> None:
        if not ok:
            failures.append(msg)

    pk = doc["packing"]
    pin(pk["all_terminated"], "packing leg left non-terminated trials")
    pin(
        pk["makespan_over_naive"] is not None
        and pk["makespan_over_naive"] < 0.6,
        f"makespan {pk['makespan_s']}s is "
        f"{pk['makespan_over_naive']}x naive sequential (pin: < 0.6x)",
    )
    pin(
        pk["chip_idle_fraction"] is not None
        and pk["chip_idle_fraction"] < 0.25,
        f"chip_idle_fraction {pk['chip_idle_fraction']} (pin: < 0.25)",
    )

    kl = doc["kill"]
    pin(
        kl["trial_state"] == "TERMINATED",
        f"killed trial ended {kl['trial_state']}",
    )
    pin(kl["attempts"] >= 2, "kill leg never migrated")
    pin(
        kl["journaled_preemptions"] >= 1,
        "migration missing from the journaled sweep table",
    )
    pin(
        kl["steps_lost_per_kill"] <= 1,
        f"kill re-ran {kl['steps_lost_per_kill']} steps (pin: <= 1)",
    )

    fk = doc["fork"]
    pin(fk["forks"] >= 1, "fork leg produced no PBT exploit")
    for rec in fk["fork_recs"]:
        pin(
            rec["new_chunks"] == 0 and rec["dedup_ratio"] == 1.0,
            f"fork {rec['winner']}->{rec['loser']} moved "
            f"{rec['new_chunks']} new chunks "
            f"(dedup {rec['dedup_ratio']})",
        )
    return failures


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--chips", type=int, default=4)
    ap.add_argument(
        "--output", default=os.path.join(REPO, "BENCH_tune.json")
    )
    args = ap.parse_args()

    import tempfile

    doc = {"bench": "tune_sweep", "trials": args.trials}
    doc["packing"] = leg_packing(args.trials, args.steps, args.chips)
    with tempfile.TemporaryDirectory(prefix="bench-tune-") as tmp:
        doc["kill"] = leg_kill(tmp)
    doc["fork"] = leg_fork()

    failures = apply_pins(doc)
    doc["pins"] = {"failures": failures, "passed": not failures}

    with open(args.output, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    print(f"wrote {args.output}")
    for msg in failures:
        print(f"FAIL: {msg}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
